"""Repo-specific AST lint: rules generic linters cannot know.

Eight rule classes have bitten this codebase (or its measured history)
and are mechanically checkable from the AST:

* **CTYPES001** — the native scanner boundary.  The C ABI's ``c_char``
  takes EXACTLY one byte; ctypes raises a cryptic ``TypeError`` (or
  silently truncates, for sliced bytes) when a multi-byte encoding of a
  user-supplied delimiter/comment reaches it.  Every ``.encode(...)``
  expression flowing into a ``c_char`` parameter position (positions are
  discovered from the module's own ``lib.X.argtypes = [...]``
  assignments) must be gated in the same function by a
  ``len(<that expression>) == 1`` / ``!= 1`` test or an explicit
  single-byte slice ``[0:1]``.  The round-5 fused-path bug — a
  multi-byte delimiter reaching ``csv_scan_parse_i32`` ungated — is
  exactly this rule.
* **JIT001** — the retrace boundary.  A ``jax.jit``-ed function whose
  body iterates one of its PARAMETERS in a comprehension has a
  tuple-of-arrays signature: every distinct tuple LENGTH is a fresh
  trace + compile (one per chunk-count in the ingest profile).  Such
  kernels should be eager, take a fixed arity, or carry an explicit
  suppression acknowledging the retrace cost.
* **TRACE001** — the trace-churn boundary (the ``_values_concat``
  regression class).  A jit-wrapped callable CONSTRUCTED inside a
  function body is rebuilt — and retraced — on every call; jit
  construction with a non-hashable ``static_argnums``/``static_argnames``
  literal fails at first call.  Sanctioned shapes: module-level jitted
  kernels (``_translate_dense_kernel``), and construction memoized into
  module-owned state (a ``global``-declared name, or a module-level
  cache like ``_JIT_KERNELS.update(...)``) so it happens once.
* **EAGER001** — the unfused-hot-loop boundary (the r06 regression:
  eager per-column translate/pack loops cost 3x the warm sharded join).
  A plain Python ``for`` loop in a HOT module (``ops/``,
  ``columnar/typed.py``, ``columnar/table.py``) issuing two or more
  unfused jnp element-wise transforms per iteration, outside any jit
  context (neither jit-decorated nor called from a same-module jitted
  kernel), dispatches each op eagerly per column per execution.
* **THREAD001** — the worker-purity boundary (the r07 invariant: "all
  cross-chunk state lives in the reassembler").  In a module defining a
  stream worker entry (``_scan_encode_chunk``), no function reachable
  from the worker may mutate module-global state (or the shared context
  argument) — except under a module-level ``threading.Lock``/``RLock``
  ``with`` block (double-checked pool/library init) or into
  ``threading.local()`` storage.
* **LOCK001** — the lock-ordering boundary (ISSUE 16).  Two code paths
  nesting the same pair of locks in opposite orders deadlock under
  contention.  The repo's monitors (serve dispatcher, storage
  writer/compactor, views refresh, obs plane) follow a constant-lock-
  rounds discipline — one lock, bounded work, release — so ANY
  lexically nested acquisition of two recognized locks (module-level
  ``Lock``/``RLock`` names, ``*lock``/``*cv`` attributes) is flagged
  unless the ordered pair appears in the single canonical order table
  ``LOCK001_CANONICAL_ORDER`` (one documented entry: the views refresh
  pass).  The allowance list stays empty — sanctioned nesting is an
  ordering fact, not a per-site waiver.
* **FAULT001** — the silent-swallow boundary (ISSUE 8).  The reference
  error contract says every failure surfaces typed and row-annotated
  (csvplus.go:1229-1238), but a broad ``except``/``except Exception``/
  ``except BaseException`` handler whose body is ONLY ``pass``/
  ``continue`` silently discards whatever went wrong.  Handlers must
  re-raise, wrap via ``map_error``, or record the failure to
  metrics/telemetry/stderr; narrowly-typed best-effort catches
  (``except (OSError, AttributeError):``) remain legal.
* **IO001** — the durability boundary (ISSUE 10).  Under ``storage/``,
  a bare ``open()`` with a write mode in a function that neither
  ``os.fsync``-es nor publishes via ``os.replace``/``os.rename`` can
  ack data that exists only in the page cache — the acked-then-lost
  window the WAL/manifest machinery exists to close.

Each of TRACE001/EAGER001/THREAD001/LOCK001/FAULT001/IO001 carries an explicit
allowance list below (``*_ALLOWED``) that STARTS EMPTY and must stay
empty for the current tree; additions need review.

Suppression: a ``# analysis: allow[CODE]`` comment on the flagged line
or on the enclosing ``def`` line.

Run over the tree with ``python -m csvplus_tpu.analysis`` (no
arguments = the whole installed package tree, so a new module can never
bypass the gate; wired into ``make lint``).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Set, Tuple

__all__ = ["LintFinding", "lint_source", "lint_file", "lint_paths"]


@dataclass(frozen=True)
class LintFinding:
    code: str  # "CTYPES001" | "JIT001" | "TRACE001" | "EAGER001" | "THREAD001" | "LOCK001" | "FAULT001" | "IO001"
    path: str
    line: int
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: {self.code} {self.message}"


def _is_c_char(node: ast.expr) -> bool:
    return (isinstance(node, ast.Attribute) and node.attr == "c_char") or (
        isinstance(node, ast.Name) and node.id == "c_char"
    )


def _c_char_positions(tree: ast.Module) -> Dict[str, Tuple[int, ...]]:
    """``{function_name: c_char argument positions}`` from every
    ``<lib>.NAME.argtypes = [...]`` assignment in the module."""
    out: Dict[str, Tuple[int, ...]] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        tgt = node.targets[0]
        if not (
            isinstance(tgt, ast.Attribute)
            and tgt.attr == "argtypes"
            and isinstance(tgt.value, ast.Attribute)
        ):
            continue
        if not isinstance(node.value, (ast.List, ast.Tuple)):
            continue
        pos = tuple(
            i for i, el in enumerate(node.value.elts) if _is_c_char(el)
        )
        if pos:
            out[tgt.value.attr] = pos
    return out


def _find_encode(node: ast.expr) -> Optional[ast.Call]:
    """The ``<something>.encode(...)`` call inside *node*, if any."""
    for sub in ast.walk(node):
        if (
            isinstance(sub, ast.Call)
            and isinstance(sub.func, ast.Attribute)
            and sub.func.attr == "encode"
        ):
            return sub
    return None


def _is_single_byte_slice(node: ast.expr) -> bool:
    """``X[0:1]`` — an explicit truncation to at most one byte."""
    if not (isinstance(node, ast.Subscript) and isinstance(node.slice, ast.Slice)):
        return False
    s = node.slice
    return (
        isinstance(s.lower, ast.Constant)
        and s.lower.value == 0
        and isinstance(s.upper, ast.Constant)
        and s.upper.value == 1
        and s.step is None
    )


def _len_one_guards(func: ast.AST) -> Set[str]:
    """Unparsed sources ``X`` for every ``len(X) == 1`` / ``len(X) != 1``
    comparison anywhere in *func* (either operand order)."""
    out: Set[str] = set()

    def record(len_side: ast.expr, const_side: ast.expr) -> None:
        if (
            isinstance(len_side, ast.Call)
            and isinstance(len_side.func, ast.Name)
            and len_side.func.id == "len"
            and len(len_side.args) == 1
            and isinstance(const_side, ast.Constant)
            and const_side.value == 1
        ):
            out.add(ast.unparse(len_side.args[0]))

    for node in ast.walk(func):
        if not isinstance(node, ast.Compare) or len(node.ops) != 1:
            continue
        if not isinstance(node.ops[0], (ast.Eq, ast.NotEq)):
            continue
        record(node.left, node.comparators[0])
        record(node.comparators[0], node.left)
    return out


def _local_assignments(func: ast.AST) -> Dict[str, ast.expr]:
    """Simple single-target ``name = expr`` bindings in *func* (last one
    wins — good enough for the guard-resolution heuristic)."""
    out: Dict[str, ast.expr] = {}
    for node in ast.walk(func):
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
        ):
            out[node.targets[0].id] = node.value
    return out


class _FunctionStack(ast.NodeVisitor):
    """Visitor that tracks the enclosing function for every node."""

    def __init__(self) -> None:
        self.stack: List[ast.AST] = []

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self.stack.append(node)
        self.generic_visit(node)
        self.stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]

    @property
    def current(self) -> Optional[ast.AST]:
        return self.stack[-1] if self.stack else None


class _CtypesVisitor(_FunctionStack):
    def __init__(self, positions: Dict[str, Tuple[int, ...]], path: str):
        super().__init__()
        self.positions = positions
        self.path = path
        self.findings: List[LintFinding] = []

    def visit_Call(self, node: ast.Call) -> None:
        self.generic_visit(node)
        fn = node.func
        if not (isinstance(fn, ast.Attribute) and fn.attr in self.positions):
            return
        func = self.current
        guards = _len_one_guards(func) if func is not None else set()
        local = _local_assignments(func) if func is not None else {}
        for pos in self.positions[fn.attr]:
            if pos >= len(node.args):
                continue
            arg = node.args[pos]
            name = None
            if isinstance(arg, ast.Name):
                name = arg.id
                arg = local.get(arg.id, arg)
            enc = _find_encode(arg)
            if enc is None:
                continue
            if _is_single_byte_slice(arg):
                continue
            gate_keys = {ast.unparse(arg), ast.unparse(enc)}
            if name is not None:
                gate_keys.add(name)
            if gate_keys & guards:
                continue
            self.findings.append(
                LintFinding(
                    "CTYPES001",
                    self.path,
                    node.args[pos].lineno,
                    f"{ast.unparse(enc)} flows into c_char parameter "
                    f"{pos} of {fn.attr} without a len(...) == 1 gate "
                    "in the enclosing function",
                )
            )


def _is_jit_decorator(dec: ast.expr) -> bool:
    """``@jax.jit``, ``@jit``, or any decorator CALL mentioning ``jit``
    (``functools.partial(jax.jit, ...)``)."""
    for node in ast.walk(dec):
        if isinstance(node, ast.Attribute) and node.attr == "jit":
            return True
        if isinstance(node, ast.Name) and node.id == "jit":
            return True
    return False


class _JitVisitor(ast.NodeVisitor):
    def __init__(self, path: str):
        self.path = path
        self.findings: List[LintFinding] = []

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self.generic_visit(node)
        if not any(_is_jit_decorator(d) for d in node.decorator_list):
            return
        params = {
            a.arg
            for a in (
                node.args.posonlyargs + node.args.args + node.args.kwonlyargs
            )
        }

        def iterates_param(it: ast.expr) -> Optional[str]:
            if isinstance(it, ast.Name) and it.id in params:
                return it.id
            # zip(maps, cks) / enumerate(cks) over parameters
            if isinstance(it, ast.Call):
                for a in it.args:
                    if isinstance(a, ast.Name) and a.id in params:
                        return a.id
            return None

        # one finding per function: the signature is the problem, not
        # each comprehension that exhibits it
        for sub in ast.walk(node):
            if isinstance(
                sub, (ast.ListComp, ast.SetComp, ast.GeneratorExp, ast.For)
            ):
                its = (
                    [g.iter for g in sub.generators]
                    if not isinstance(sub, ast.For)
                    else [sub.iter]
                )
                for it in its:
                    hit = iterates_param(it)
                    if hit is not None:
                        self.findings.append(
                            LintFinding(
                                "JIT001",
                                self.path,
                                sub.lineno,
                                f"jit-compiled `{node.name}` iterates "
                                f"parameter `{hit}`: a tuple-of-arrays "
                                "signature retraces per distinct length",
                            )
                        )
                        return


# ---------------------------------------------------------------------------
# TRACE001 / EAGER001 / THREAD001 — regression-derived rules (ISSUE 5).
# Allowance lists start EMPTY and must stay empty on the current tree:
# entries are "<file basename>:<enclosing function>" and need review.
# ---------------------------------------------------------------------------

TRACE001_ALLOWED: frozenset = frozenset()
EAGER001_ALLOWED: frozenset = frozenset()
THREAD001_ALLOWED: frozenset = frozenset()
FAULT001_ALLOWED: frozenset = frozenset()
IO001_ALLOWED: frozenset = frozenset()
LOCK001_ALLOWED: frozenset = frozenset()

#: LOCK001's canonical lock-order table: the ONLY sanctioned nested
#: acquisitions, as ``(outer identity, inner identity)`` pairs (see
#: ``_lock_identity`` for the identity format: ``Owner.attr`` for
#: attribute locks, ``module_stem.name`` for module-level locks).  The
#: repo's concurrency discipline is CONSTANT LOCK ROUNDS — take one
#: lock, do bounded work, release, then take the next (the r08 metrics
#: cycle, joinskew's registry-then-sketch sequence, the plan cache's
#: verify-outside-the-lock miss path) — so any lexical nesting of two
#: recognized locks is a finding until the pair is reviewed, documented
#: here, and ordered once for the whole repo.  Current entries:
#:
#: * ``MaterializedView._lock -> MaterializedView._qlock`` — the
#:   refresh pass (serialized by ``_lock``) dequeues tier events under
#:   the O(1) queue guard; every other ``_qlock`` use is a leaf (no
#:   lock acquired inside it), so the order is total and deadlock-free.
LOCK001_CANONICAL_ORDER: frozenset = frozenset({
    ("MaterializedView._lock", "MaterializedView._qlock"),
})

# modules whose per-row loops sit on the measured hot path (r06)
_EAGER_HOT_DIRS = ("ops",)
_EAGER_HOT_FILES = ("typed.py", "table.py")

# Cross-thread entry points whose reachable call graph must mutate
# shared state only under locks: the r07 ingest worker, plus the r08
# serving tier's dispatcher loop and its caller-side submission path
# and the serving monitors' mutators (metrics counters/reservoirs, the
# plan-cache map), plus the r09 observability subsystem's entry points
# (telemetry mutators, the tracer's cross-thread recorders, the kernel
# registry, and the memory sampler loop — all called from ingest
# workers, the serve dispatcher, and submitters concurrently).
# Matching is on the bare name, so class METHODS with these names are
# entries too (the lint tracks ``self`` as the shared context).
_WORKER_ENTRY_NAMES = (
    "_scan_encode_chunk",
    "_dispatch_loop",
    "_enqueue",
    "on_tick",
    "on_batch",
    "on_enqueue",
    "on_shed",
    "on_complete_batch",
    "executable_for",
    # csvplus_tpu/obs + utils/observe entry points (r09)
    "add_stage",
    "count",
    "count_sync",
    "add_span",
    "record_span",
    "drain",
    "register_kernel",
    "_sample_loop",
    # csvplus_tpu/resilience entry points (ISSUE 8): the fault plan's
    # hit-counter mutator (armed chaos runs hit it from every worker,
    # dispatcher, and submitter thread), the circuit breaker's
    # route/outcome mutators, and the new serving-metrics counters
    # (retry / degrade / callback-error accounting).
    "fire",
    "route",
    "on_success",
    "on_failure",
    "on_retry",
    "on_degraded",
    "on_callback_error",
    # csvplus_tpu/storage entry points (ISSUE 9): the mutable index's
    # writers (append batches land from caller threads and the serve
    # dispatcher; compact_once races both), the compactor's background
    # loop, and the serving tier's registry/append/per-index-metrics
    # mutators.
    "append_rows",
    "append_table",
    "append_csv",
    "compact_once",
    "_compact_loop",
    "run_once",
    "register",
    "submit_append",
    "on_index_batch",
    "on_compact",
    # csvplus_tpu/storage durability entry points (ISSUE 10): the
    # tombstone writer and leveled-compaction step race appends and the
    # compactor like the r09 writers; the WAL's record/seal/drop
    # mutators are hit from every writer thread AND the compactor's
    # checkpoint; wal_sync is the serve dispatcher's per-cycle fsync
    # barrier; on_recovered lands recovery counts into the serving
    # metrics monitor at registration time.
    "delete",
    "compact_step",
    "wal_sync",
    "append_record",
    "sync_now",
    "seal_active",
    "drop_applied",
    "on_recovered",
    # csvplus_tpu/storage read-pruning entry points (ISSUE 11): the
    # multi-tier probe path itself (serving threads call bounds_many
    # concurrently with writers swapping tier sets — its lazy builds
    # must stay lock-guarded), and the read-amplification tracker's
    # recorder/window mutators (hit from every reader thread and the
    # readamp-policy compactor loop).
    "bounds_many",
    "on_lookup_batch",
    "take_window",
    # csvplus_tpu/views + serve view entry points (ISSUE 12): the
    # tier-swap listener registry mutators and the event-intake
    # callback (fired UNDER the source's writer lock from every writer
    # thread), the refresh pass (serve dispatcher + caller threads) and
    # the lock-free snapshot read path, the server's view registration
    # and delete submission, the per-view metrics mutators, and the
    # lazy pruner/prune-directory builds the probe path races against
    # tier swaps (made lazy in this issue).
    "subscribe",
    "unsubscribe",
    "_on_tier_event",
    "refresh",
    "read",
    "register_view",
    "submit_delete",
    "on_view_refresh",
    "on_view_read",
    "ensure_pruner",
    "prune_directory",
    # csvplus_tpu/obs/joinskew + ops/join skew entry points (ISSUE 15):
    # the partitioned probe's routing-evidence mutators (hit from every
    # pipeline/ingest/serve thread that executes a sharded join) and
    # the index's once-only build-sample offer (first probe or point
    # lookup wins the race under the aux lock).
    "on_join",
    "offer_build",
    "offer_build_sample",
    # csvplus_tpu/obs/joinskew multiway entry point (ISSUE 17): the
    # fused single-pass join's evidence mutator — same concurrency
    # envelope as on_join (any thread executing a multiway join).
    "on_multiway",
)

_EAGER_TRANSFORM_OPS = frozenset(
    {
        "where",
        "take",
        "take_along_axis",
        "clip",
        "searchsorted",
        "minimum",
        "maximum",
        "equal",
        "not_equal",
        "greater",
        "greater_equal",
        "less",
        "less_equal",
        "left_shift",
        "right_shift",
        "bitwise_or",
        "bitwise_and",
        "bitwise_xor",
        "add",
        "subtract",
        "multiply",
        "sum",
        "cumsum",
        "select",
    }
)

_MUTATING_METHODS = frozenset(
    {
        "append",
        "add",
        "update",
        "extend",
        "insert",
        "pop",
        "popitem",
        "remove",
        "discard",
        "clear",
        "setdefault",
        "sort",
        "reverse",
        # deque / OrderedDict mutators the serving tier's queues and
        # LRUs lean on (r08)
        "popleft",
        "appendleft",
        "move_to_end",
    }
)


def _allow_key(path: str, func: Optional[ast.AST]) -> str:
    name = getattr(func, "name", "<module>") if func is not None else "<module>"
    return f"{Path(path).name}:{name}"


def _module_level_names(tree: ast.Module) -> Set[str]:
    """Names bound at module scope (assignments, defs, imports)."""
    out: Set[str] = set()
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                for n in ast.walk(t):
                    if isinstance(n, ast.Name):
                        out.add(n.id)
        elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
            if isinstance(stmt.target, ast.Name):
                out.add(stmt.target.id)
        elif isinstance(
            stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
        ):
            out.add(stmt.name)
        elif isinstance(stmt, (ast.Import, ast.ImportFrom)):
            for a in stmt.names:
                out.add((a.asname or a.name).split(".")[0])
    return out


def _jit_construction(call: ast.Call) -> bool:
    """A call whose RESULT is a jit-wrapped callable: ``jax.jit(...)``,
    ``jit(...)``, or ``functools.partial(jax.jit, ...)``."""
    f = call.func
    if isinstance(f, ast.Attribute) and f.attr == "jit":
        return True
    if isinstance(f, ast.Name) and f.id == "jit":
        return True
    if (isinstance(f, ast.Attribute) and f.attr == "partial") or (
        isinstance(f, ast.Name) and f.id == "partial"
    ):
        return bool(call.args) and _is_jit_decorator(call.args[0])
    return False


def _declared_globals(func: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for n in ast.walk(func):
        if isinstance(n, ast.Global):
            out.update(n.names)
    return out


def _root_name(node: ast.expr) -> Optional[str]:
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


class _TraceVisitor(_FunctionStack):
    """TRACE001: jit construction inside a function body (unless stored
    into module-owned state) and non-hashable static-arg literals."""

    def __init__(self, path: str, tree: ast.Module):
        super().__init__()
        self.path = path
        self.module_names = _module_level_names(tree)
        self.findings: List[LintFinding] = []
        # decorator expressions are governed by the FunctionDef branch,
        # not the Call branch (a nested `@partial(jax.jit, ...)` def is
        # one construction, not two)
        self._decorator_nodes = {
            id(sub)
            for f in ast.walk(tree)
            if isinstance(f, (ast.FunctionDef, ast.AsyncFunctionDef))
            for d in f.decorator_list
            for sub in ast.walk(d)
        }

    def _flag(self, line: int, func: Optional[ast.AST], message: str) -> None:
        if _allow_key(self.path, func) in TRACE001_ALLOWED:
            return
        self.findings.append(LintFinding("TRACE001", self.path, line, message))

    def _stores_to_module_state(self, outer: ast.AST, match) -> bool:
        """True when an assignment in *outer* whose value satisfies
        *match* targets a ``global``-declared name, a module-level name,
        or a subscript/attribute of one — the sanctioned memoization."""
        owned = _declared_globals(outer) | self.module_names
        for n in ast.walk(outer):
            if isinstance(n, ast.Assign) and match(n.value):
                for t in n.targets:
                    if isinstance(t, ast.Name) and t.id in owned:
                        return True
                    if isinstance(t, (ast.Subscript, ast.Attribute)):
                        root = _root_name(t)
                        if root is not None and root in owned:
                            return True
        return False

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        outer = self.current
        if outer is not None and any(
            _is_jit_decorator(d) for d in node.decorator_list
        ):
            escapes = self._stores_to_module_state(
                outer,
                lambda v: any(
                    isinstance(s, ast.Name) and s.id == node.name
                    for s in ast.walk(v)
                ),
            )
            if not escapes:
                self._flag(
                    node.lineno,
                    outer,
                    f"jit-wrapped `{node.name}` is constructed inside "
                    f"`{outer.name}`: retraced on every call — hoist to a "
                    "module-level kernel or memoize into module state",
                )
        self.stack.append(node)
        self.generic_visit(node)
        self.stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]

    def visit_Call(self, node: ast.Call) -> None:
        self.generic_visit(node)
        if not _jit_construction(node):
            return
        func = self.current
        for kw in node.keywords:
            if kw.arg in ("static_argnums", "static_argnames") and isinstance(
                kw.value, (ast.Dict, ast.Set, ast.DictComp, ast.SetComp)
            ):
                self._flag(
                    node.lineno,
                    func,
                    f"jit construction passes a non-hashable {kw.arg} "
                    "literal — fails (or cache-misses) at first call",
                )
        if id(node) in self._decorator_nodes:
            return
        if func is None:
            return  # module-level jitted kernels are THE sanctioned shape
        if self._stores_to_module_state(
            func, lambda v: any(s is node for s in ast.walk(v))
        ):
            return
        # a module-cache method call, e.g. _JIT_KERNELS.update(k=jax.jit(f))
        for n in ast.walk(func):
            if (
                isinstance(n, ast.Call)
                and n is not node
                and isinstance(n.func, ast.Attribute)
                and _root_name(n.func) in self.module_names
                and any(s is node for s in ast.walk(n))
            ):
                return
        self._flag(
            node.lineno,
            func,
            f"jit-wrapped callable constructed inside `{func.name}`: "
            "retraced on every call — hoist to a module-level kernel or "
            "memoize into module state",
        )


def _is_hot_module(path: str) -> bool:
    p = Path(path)
    return p.name in _EAGER_HOT_FILES or any(
        d in _EAGER_HOT_DIRS for d in p.parts[:-1]
    )


def _jit_context_names(tree: ast.Module) -> Set[str]:
    """Function names that execute under jit in THIS module: defs with a
    jit decorator, defs passed to a jit construction, and everything
    they transitively call by local name."""
    defs: Dict[str, List[ast.AST]] = {}
    for n in ast.walk(tree):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs.setdefault(n.name, []).append(n)
    roots: Set[str] = set()
    for name, nodes in defs.items():
        if any(
            _is_jit_decorator(dec) for d in nodes for dec in d.decorator_list
        ):
            roots.add(name)
    for n in ast.walk(tree):
        if isinstance(n, ast.Call) and _jit_construction(n) and n.args:
            a = n.args[0]
            if isinstance(a, ast.Name) and a.id in defs:
                roots.add(a.id)
    seen: Set[str] = set()
    work = list(roots)
    while work:
        name = work.pop()
        if name in seen:
            continue
        seen.add(name)
        for d in defs.get(name, []):
            for sub in ast.walk(d):
                if isinstance(sub, ast.Call) and isinstance(sub.func, ast.Name):
                    if sub.func.id in defs and sub.func.id not in seen:
                        work.append(sub.func.id)
    return seen


def _eager_counted_call(sub: ast.AST) -> bool:
    if not isinstance(sub, ast.Call) or not isinstance(sub.func, ast.Attribute):
        return False
    f = sub.func
    if f.attr == "astype":
        # only a jnp-dtype astype is a device dispatch; numpy astypes
        # (host packers) are not the r06 shape
        return (
            bool(sub.args)
            and isinstance(sub.args[0], ast.Attribute)
            and isinstance(sub.args[0].value, ast.Name)
            and sub.args[0].value.id == "jnp"
        )
    root = f.value
    while isinstance(root, ast.Attribute):
        root = root.value
    if isinstance(root, ast.Name) and root.id in ("jnp", "jax", "lax"):
        return f.attr in _EAGER_TRANSFORM_OPS
    return False


_EAGER_BINOPS = (
    ast.BitOr,
    ast.BitAnd,
    ast.BitXor,
    ast.LShift,
    ast.RShift,
    ast.Add,
    ast.Sub,
    ast.Mult,
)


def _eager_score(loop: ast.For) -> int:
    """Unfused element-wise device dispatches per loop iteration:
    jnp/lax transform calls, jnp-dtype ``.astype``, and arithmetic/bit
    operators whose operands contain one (each eager ``|``/``<<``/``+``
    over jax arrays is its own dispatch — the r06 pack-loop shape)."""
    count = 0
    for sub in ast.walk(loop):
        if _eager_counted_call(sub):
            count += 1
        elif isinstance(sub, ast.BinOp) and isinstance(sub.op, _EAGER_BINOPS):
            if any(_eager_counted_call(s) for s in ast.walk(sub)):
                count += 1
        elif isinstance(sub, ast.AugAssign) and isinstance(
            sub.op, _EAGER_BINOPS
        ):
            if any(_eager_counted_call(s) for s in ast.walk(sub.value)):
                count += 1
    return count


class _EagerVisitor(_FunctionStack):
    """EAGER001: eager per-column loops in hot modules (r06 shape)."""

    def __init__(self, path: str, tree: ast.Module):
        super().__init__()
        self.path = path
        self.jit_names = _jit_context_names(tree)
        self.findings: List[LintFinding] = []

    def _in_jit_context(self) -> bool:
        for f in self.stack:
            if f.name in self.jit_names or any(
                _is_jit_decorator(d) for d in f.decorator_list
            ):
                return True
        return False

    def visit_For(self, node: ast.For) -> None:
        if not self._in_jit_context():
            score = _eager_score(node)
            if score >= 2 and _allow_key(self.path, self.current) not in (
                EAGER001_ALLOWED
            ):
                self.findings.append(
                    LintFinding(
                        "EAGER001",
                        self.path,
                        node.lineno,
                        f"eager loop issues {score} unfused jnp element-wise "
                        "dispatches per iteration in a hot module — fuse "
                        "into a module-level jitted kernel (r06 regression "
                        "shape)",
                    )
                )
        self.generic_visit(node)


def _lock_names(tree: ast.Module) -> Set[str]:
    out: Set[str] = set()
    for stmt in tree.body:
        if not (isinstance(stmt, ast.Assign) and isinstance(stmt.value, ast.Call)):
            continue
        f = stmt.value.func
        attr = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else None
        )
        if attr in ("Lock", "RLock"):
            for t in stmt.targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
    return out


def _thread_local_names(tree: ast.Module) -> Set[str]:
    out: Set[str] = set()
    for stmt in tree.body:
        if not (isinstance(stmt, ast.Assign) and isinstance(stmt.value, ast.Call)):
            continue
        f = stmt.value.func
        if isinstance(f, ast.Attribute) and f.attr == "local":
            for t in stmt.targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
    return out


def _thread_findings(tree: ast.Module, path: str) -> List[LintFinding]:
    """THREAD001 over one module, active only when it defines a worker
    entry (:data:`_WORKER_ENTRY_NAMES`; module-level functions AND
    methods of module-level classes match by bare name).  Walks the
    same-module call graph from each entry — through plain calls and
    through ``ctx.method(...)`` calls on a tracked context — propagating
    which parameters alias the SHARED context (the entry's first
    argument; ``self`` for a method entry), and flags any mutation of
    module-global or shared-context state outside a lock's ``with``
    block or ``threading.local()`` storage.  Recognized guards: a
    module-level ``Lock``/``RLock`` name, or an attribute of the
    tracked context / a module global whose terminal name ends in
    ``lock`` or ``cv`` (``with self._lock:``, ``with ctx._cv:`` — a
    Condition's ``with`` acquires its underlying lock)."""
    defs: Dict[str, ast.AST] = {}
    method_index: Dict[str, List[str]] = {}  # bare method name -> "Cls.m" keys
    for stmt in tree.body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs[stmt.name] = stmt
        elif isinstance(stmt, ast.ClassDef):
            for sub in stmt.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    q = f"{stmt.name}.{sub.name}"
                    defs[q] = sub
                    method_index.setdefault(sub.name, []).append(q)
    entries = [
        name
        for name in defs
        if name.rsplit(".", 1)[-1] in _WORKER_ENTRY_NAMES
    ]
    if not entries:
        return []
    module_names = _module_level_names(tree)
    locks = _lock_names(tree)
    tlocals = _thread_local_names(tree)

    def params_of(func: ast.AST) -> List[str]:
        a = func.args
        return [p.arg for p in a.posonlyargs + a.args]

    # reachable functions with the set of parameters aliasing the shared
    # context, to a fixpoint (conservative union across call sites)
    tracked: Dict[str, Set[str]] = {}
    for e in entries:
        ps = params_of(defs[e])
        tracked[e] = {ps[0]} if ps else set()

    def propagate(callee: str, passed: Set[str], work: List[str]) -> None:
        prev = tracked.get(callee)
        if prev is None or not passed <= prev:
            tracked[callee] = (prev or set()) | passed
            work.append(callee)

    work = list(entries)
    while work:
        name = work.pop()
        func = defs[name]
        t = tracked.get(name, set())
        for sub in ast.walk(func):
            if not isinstance(sub, ast.Call):
                continue
            callees: List[Tuple[str, int]] = []  # (def key, self offset)
            if isinstance(sub.func, ast.Name) and sub.func.id in defs:
                callees.append((sub.func.id, 0))
            elif (
                isinstance(sub.func, ast.Attribute)
                and isinstance(sub.func.value, ast.Name)
                and sub.func.value.id in t
            ):
                # ctx.method(...): the receiver IS the shared context —
                # resolve to every same-module class method of that name
                # (conservative when classes share a method name)
                callees.extend(
                    (q, 1) for q in method_index.get(sub.func.attr, ())
                )
            for callee, offset in callees:
                callee_params = params_of(defs[callee])
                passed: Set[str] = set()
                if offset and callee_params:
                    passed.add(callee_params[0])  # receiver binds self
                for i, a in enumerate(sub.args):
                    j = i + offset
                    if (
                        isinstance(a, ast.Name)
                        and a.id in t
                        and j < len(callee_params)
                    ):
                        passed.add(callee_params[j])
                for kw in sub.keywords:
                    if (
                        kw.arg is not None
                        and isinstance(kw.value, ast.Name)
                        and kw.value.id in t
                    ):
                        passed.add(kw.arg)
                propagate(callee, passed, work)

    findings: List[LintFinding] = []
    for name, ctx_params in tracked.items():
        func = defs[name]

        def _is_lock_expr(expr: ast.expr) -> bool:
            if isinstance(expr, ast.Name):
                return expr.id in locks
            if isinstance(expr, ast.Attribute):
                root = _root_name(expr)
                tail = expr.attr
                return root is not None and (
                    root in ctx_params or root in module_names
                ) and (tail.endswith("lock") or tail.endswith("cv"))
            return False

        spans = [
            (w.lineno, getattr(w, "end_lineno", w.lineno))
            for w in ast.walk(func)
            if isinstance(w, ast.With)
            and any(_is_lock_expr(item.context_expr) for item in w.items)
        ]
        g = _declared_globals(func)

        def lock_guarded(line: int) -> bool:
            return any(lo <= line <= hi for lo, hi in spans)

        def flag(line: int, what: str) -> None:
            if _allow_key(path, func) in THREAD001_ALLOWED:
                return
            findings.append(
                LintFinding(
                    "THREAD001",
                    path,
                    line,
                    f"`{name}` is reachable from worker entry "
                    f"`{'/'.join(sorted(entries))}` and {what} outside a "
                    "recognized lock — shared mutable state must be "
                    "lock-guarded or owned by one thread (r07/r08 "
                    "invariant)",
                )
            )

        def check_store_target(t: ast.expr, line: int) -> None:
            if isinstance(t, (ast.Tuple, ast.List)):
                for el in t.elts:
                    check_store_target(el, line)
                return
            if isinstance(t, ast.Name):
                if t.id in g and not lock_guarded(line):
                    flag(line, f"stores module global `{t.id}`")
                return
            if isinstance(t, (ast.Attribute, ast.Subscript)):
                root = _root_name(t)
                if root is None or root in tlocals or lock_guarded(line):
                    return
                if root in ctx_params:
                    flag(line, f"mutates the shared context `{root}`")
                elif root in g or (root in module_names and root not in defs):
                    flag(line, f"mutates module-global `{root}`")

        for sub in ast.walk(func):
            if isinstance(sub, ast.Assign):
                for t in sub.targets:
                    check_store_target(t, sub.lineno)
            elif isinstance(sub, (ast.AugAssign, ast.AnnAssign)):
                check_store_target(sub.target, sub.lineno)
            elif (
                isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Attribute)
                and sub.func.attr in _MUTATING_METHODS
            ):
                root = _root_name(sub.func)
                if (
                    root is not None
                    and root not in tlocals
                    and not lock_guarded(sub.lineno)
                ):
                    if root in ctx_params:
                        flag(
                            sub.lineno,
                            f"calls `{root}.{sub.func.attr}(...)` on the "
                            "shared context",
                        )
                    elif root in module_names and root not in defs:
                        flag(
                            sub.lineno,
                            f"calls `{root}.{sub.func.attr}(...)` on a "
                            "module global",
                        )
    return findings


def _io_findings(tree: ast.Module, path: str) -> List[LintFinding]:
    """IO001, active only under ``storage/``: a bare ``open()`` with a
    write mode (``w``/``a``/``x``/``+``) in a function that neither
    fsyncs nor publishes via atomic rename leaves a durability hole —
    the data may sit in the page cache when the ack goes out, exactly
    the acked-then-lost window the WAL exists to close.  Write through
    the fsync-then-rename idiom (``wal._open_segment``,
    ``manifest.write_manifest``) or fsync in the same function."""
    if "storage" not in Path(path).parts:
        return []
    findings: List[LintFinding] = []
    for node in ast.walk(tree):
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "open"
        ):
            continue
        mode: Optional[str] = None
        if (
            len(node.args) >= 2
            and isinstance(node.args[1], ast.Constant)
            and isinstance(node.args[1].value, str)
        ):
            mode = node.args[1].value
        for kw in node.keywords:
            if (
                kw.arg == "mode"
                and isinstance(kw.value, ast.Constant)
                and isinstance(kw.value.value, str)
            ):
                mode = kw.value.value
        if mode is None or not any(ch in mode for ch in "wax+"):
            continue
        func = _enclosing_function(tree, node.lineno)
        scope = func if func is not None else tree
        durable = any(
            isinstance(sub, ast.Call)
            and isinstance(sub.func, ast.Attribute)
            and sub.func.attr in ("fsync", "replace", "rename")
            for sub in ast.walk(scope)
        )
        if durable:
            continue
        if _allow_key(path, func) in IO001_ALLOWED:
            continue
        findings.append(
            LintFinding(
                "IO001",
                path,
                node.lineno,
                f"open(..., {mode!r}) writes in storage/ without an "
                "fsync or atomic replace/rename in the enclosing "
                "function — an acked write may sit only in the page "
                "cache (use the fsync-then-rename idiom)",
            )
        )
    return findings


def _lock_identity(
    expr: ast.expr, module_locks: Set[str], class_name: Optional[str],
    stem: str
) -> Optional[str]:
    """A stable identity for a lock-like ``with`` context expression,
    or None when the expression is not lock-like.  Recognition matches
    THREAD001's: a module-level ``Lock``/``RLock`` name, or a name/
    attribute whose terminal name ends in ``lock`` or ``cv``.
    Identities are coarse on purpose — ``Owner.attr`` for attribute
    locks (the enclosing class for ``self``/``cls`` receivers),
    ``module_stem.name`` for module-level names — so the canonical
    order table ranks lock *classes*, not instances."""
    if isinstance(expr, ast.Name):
        if expr.id in module_locks or expr.id.endswith(("lock", "cv")):
            return f"{stem}.{expr.id}"
        return None
    if isinstance(expr, ast.Attribute) and expr.attr.endswith(("lock", "cv")):
        root = _root_name(expr)
        if root in ("self", "cls") and class_name is not None:
            return f"{class_name}.{expr.attr}"
        return f"{root or '?'}.{expr.attr}"
    return None


def _lock_findings(tree: ast.Module, path: str) -> List[LintFinding]:
    """LOCK001: lexically nested acquisition of two recognized locks —
    a ``with <lock>`` inside another ``with <lock>`` span (including two
    lock items in ONE ``with``, acquired left to right) — where the
    ordered ``(outer, inner)`` pair is not in
    :data:`LOCK001_CANONICAL_ORDER`.  Two code paths nesting the same
    pair of locks in opposite orders deadlock under contention; the
    repo-wide rule is one documented order or no nesting at all.  Lock
    registry covered: every module-level ``Lock``/``RLock`` plus the
    ``*lock``/``*cv`` attribute convention — the serve dispatcher,
    storage writer/compactor, views refresh, and obs plane monitors all
    follow it.  Nested ``def``/``lambda`` bodies do not execute under
    the enclosing ``with``, so the held-set resets there."""
    module_locks = _lock_names(tree)
    stem = Path(path).stem
    findings: List[LintFinding] = []

    def flag(outer: str, outer_line: int, inner: str, line: int) -> None:
        func = _enclosing_function(tree, line)
        if _allow_key(path, func) in LOCK001_ALLOWED:
            return
        findings.append(
            LintFinding(
                "LOCK001",
                path,
                line,
                f"acquires `{inner}` while holding `{outer}` (taken at "
                f"line {outer_line}) and the pair is not in the "
                "canonical lock order table "
                "(LOCK001_CANONICAL_ORDER) — nested orders must be "
                "documented once repo-wide or restructured into "
                "sequential lock rounds",
            )
        )

    def visit(node: ast.AST, held, class_name: Optional[str]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                visit(child, [], class_name)
            elif isinstance(child, ast.ClassDef):
                visit(child, held, child.name)
            elif isinstance(child, (ast.With, ast.AsyncWith)):
                now = list(held)
                for item in child.items:
                    ident = _lock_identity(
                        item.context_expr, module_locks, class_name, stem)
                    if ident is None:
                        continue
                    for outer, outer_line in now:
                        if (outer, ident) not in LOCK001_CANONICAL_ORDER:
                            flag(outer, outer_line, ident, child.lineno)
                    now.append((ident, child.lineno))
                visit(child, now, class_name)
            else:
                visit(child, held, class_name)

    visit(tree, [], None)
    return findings


# ---------------------------------------------------------------------------
# ENV001-R — the configuration registry boundary (ISSUE 20).  Every
# ``os.environ`` read routes through utils/env.py's registered
# accessors, every variable they read is declared in ENV_REGISTRY, and
# the generated docs/ENV.md matches the registry byte-for-byte.  A knob
# that exists only at its read site is invisible to operators and to
# the obs-diff lint snapshots; ~25 CSVPLUS_* vars had scattered reads
# before the registry landed.
# ---------------------------------------------------------------------------

_ENV_ACCESSORS = frozenset({"env_int", "env_str", "env_float"})


def _env_registry_names() -> Optional[frozenset]:
    """Registered variable names from the live registry module, or None
    when it cannot be imported (linting outside the package)."""
    try:
        from ..utils.env import ENV_REGISTRY
    except Exception:
        return None
    return frozenset(ENV_REGISTRY)


def _env_findings(tree: ast.Module, path: str) -> List[LintFinding]:
    """ENV001-R per-file half: direct ``os.environ``/``os.getenv`` reads
    outside utils/env.py, and accessor calls naming an unregistered (or
    non-literal) variable."""
    p = Path(path)
    if p.name == "env.py" and "utils" in p.parts:
        return []  # the one sanctioned os.environ reader
    findings: List[LintFinding] = []
    registry = _env_registry_names()
    direct_lines: Set[int] = set()
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Attribute)
            and node.attr in ("environ", "getenv")
            and isinstance(node.value, ast.Name)
            and node.value.id == "os"
        ):
            if node.lineno not in direct_lines:
                direct_lines.add(node.lineno)
                findings.append(
                    LintFinding(
                        "ENV001-R",
                        path,
                        node.lineno,
                        f"direct os.{node.attr} read — route through the "
                        "utils/env.py accessors (env_str/env_int/"
                        "env_float) so the variable lands in ENV_REGISTRY "
                        "and docs/ENV.md",
                    )
                )
        elif isinstance(node, ast.Call):
            f = node.func
            fname = None
            if isinstance(f, ast.Name):
                fname = f.id.lstrip("_")
            elif isinstance(f, ast.Attribute):
                fname = f.attr.lstrip("_")
            if fname not in _ENV_ACCESSORS or not node.args:
                continue
            first = node.args[0]
            if not (
                isinstance(first, ast.Constant) and isinstance(first.value, str)
            ):
                findings.append(
                    LintFinding(
                        "ENV001-R",
                        path,
                        node.lineno,
                        f"{fname}(...) takes a computed variable name — "
                        "names must be string literals so registration "
                        "is statically checkable",
                    )
                )
            elif registry is not None and first.value not in registry:
                findings.append(
                    LintFinding(
                        "ENV001-R",
                        path,
                        node.lineno,
                        f"{fname}({first.value!r}) reads a variable not "
                        "declared in utils/env.py ENV_REGISTRY — register "
                        "it (name, kind, default, description)",
                    )
                )
    return findings


def env_global_findings() -> List[LintFinding]:
    """ENV001-R whole-tree half, run once per lint invocation over the
    installed package: stale registry entries (declared but read
    nowhere) and generated-doc drift (committed docs/ENV.md differs
    from ``render_env_md()``)."""
    try:
        from ..utils import env as env_mod
    except Exception:
        return []
    pkg = Path(__file__).resolve().parent.parent
    reg_path = pkg / "utils" / "env.py"
    findings: List[LintFinding] = []
    sources = [
        f.read_text(encoding="utf-8")
        for f in sorted(pkg.rglob("*.py"))
        if f != reg_path
    ]
    for name in env_mod.ENV_REGISTRY:
        quoted = (f'"{name}"', f"'{name}'")
        if not any(q in src for src in sources for q in quoted):
            findings.append(
                LintFinding(
                    "ENV001-R",
                    str(reg_path),
                    1,
                    f"ENV_REGISTRY entry {name} is read nowhere in the "
                    "package — registry drift (remove it or wire the "
                    "read through an accessor)",
                )
            )
    docs = pkg.parent / "docs" / "ENV.md"
    if docs.parent.is_dir():
        rendered = env_mod.render_env_md()
        if not docs.exists():
            findings.append(
                LintFinding(
                    "ENV001-R",
                    str(docs),
                    1,
                    "generated docs/ENV.md is missing — write it with "
                    "`python -m csvplus_tpu.analysis env --write "
                    "docs/ENV.md`",
                )
            )
        elif docs.read_text(encoding="utf-8") != rendered:
            findings.append(
                LintFinding(
                    "ENV001-R",
                    str(docs),
                    1,
                    "docs/ENV.md drifted from utils/env.py ENV_REGISTRY "
                    "— regenerate with `python -m csvplus_tpu.analysis "
                    "env --write docs/ENV.md`",
                )
            )
    return findings


_BROAD_EXCEPT_NAMES = frozenset({"Exception", "BaseException"})


def _enclosing_function(tree: ast.Module, line: int) -> Optional[ast.AST]:
    """The innermost function whose span contains *line*, or None."""
    best: Optional[ast.AST] = None
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            end = getattr(node, "end_lineno", node.lineno)
            if node.lineno <= line <= end and (
                best is None or node.lineno > best.lineno
            ):
                best = node
    return best


def _fault_findings(tree: ast.Module, path: str) -> List[LintFinding]:
    """FAULT001: a broad exception handler — bare ``except``,
    ``except Exception``, ``except BaseException`` (alone or inside a
    tuple) — whose body is nothing but ``pass``/``continue``.  The
    failure is silently swallowed; the reference contract (typed,
    row-annotated, surfaced) forbids that.  Handlers that re-raise,
    wrap, return, log, or count are untouched, as are narrowly-typed
    best-effort catches."""

    def is_broad(h: ast.ExceptHandler) -> bool:
        t = h.type
        if t is None:
            return True
        elts = t.elts if isinstance(t, ast.Tuple) else [t]
        for n in elts:
            if isinstance(n, ast.Name) and n.id in _BROAD_EXCEPT_NAMES:
                return True
            if isinstance(n, ast.Attribute) and n.attr in _BROAD_EXCEPT_NAMES:
                return True
        return False

    findings: List[LintFinding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if not is_broad(node):
            continue
        if not all(isinstance(s, (ast.Pass, ast.Continue)) for s in node.body):
            continue
        func = _enclosing_function(tree, node.lineno)
        if _allow_key(path, func) in FAULT001_ALLOWED:
            continue
        findings.append(
            LintFinding(
                "FAULT001",
                path,
                node.lineno,
                "broad except handler silently swallows the error — "
                "re-raise, wrap via map_error, or record it to "
                "metrics/telemetry (the reference contract surfaces "
                "every failure typed and row-annotated)",
            )
        )
    return findings


def _suppressed(finding: LintFinding, lines: List[str], tree: ast.Module) -> bool:
    marker = f"analysis: allow[{finding.code}]"

    def line_has(idx: int) -> bool:
        return 0 < idx <= len(lines) and marker in lines[idx - 1]

    if line_has(finding.line):
        return True
    # any enclosing def line (a flagged closure inherits its outer
    # function's acknowledgment)
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            end = getattr(node, "end_lineno", node.lineno)
            if node.lineno <= finding.line <= end and line_has(node.lineno):
                return True
    return False


def lint_source(
    source: str,
    path: str = "<string>",
    matched_out=None,
) -> List[LintFinding]:
    """All unsuppressed findings for one module's source text.
    *matched_out* (a set, whole-tree lint only) accumulates the
    jitlint allowlist keys this file's sync sites matched, feeding the
    global staleness check."""
    tree = ast.parse(source, filename=path)
    findings: List[LintFinding] = []
    positions = _c_char_positions(tree)
    if positions:
        v = _CtypesVisitor(positions, path)
        v.visit(tree)
        findings.extend(v.findings)
    j = _JitVisitor(path)
    j.visit(tree)
    findings.extend(j.findings)
    t = _TraceVisitor(path, tree)
    t.visit(tree)
    findings.extend(t.findings)
    if _is_hot_module(path):
        e = _EagerVisitor(path, tree)
        e.visit(tree)
        findings.extend(e.findings)
    findings.extend(_thread_findings(tree, path))
    findings.extend(_lock_findings(tree, path))
    findings.extend(_fault_findings(tree, path))
    findings.extend(_io_findings(tree, path))
    findings.extend(_env_findings(tree, path))
    from .jitlint import jitlint_findings  # late: jitlint imports us

    findings.extend(jitlint_findings(tree, path, matched_out))
    lines = source.splitlines()
    findings = [f for f in findings if not _suppressed(f, lines, tree)]
    findings.sort(key=lambda f: (f.path, f.line, f.code))
    return findings


def lint_file(path, matched_out=None) -> List[LintFinding]:
    p = Path(path)
    return lint_source(p.read_text(encoding="utf-8"), str(p), matched_out)


def lint_paths(paths: Iterable, global_checks: bool = False) -> List[LintFinding]:
    """Lint every ``.py`` file under each path (file or directory).
    With *global_checks* (the whole-package lint run), the cross-file
    checks run once on top: the ENV001-R registry/doc drift checks and
    the jitlint allowlist staleness check (per-file lints cannot tell
    a stale allowance from a site they are not looking at)."""
    matched: set = set()
    findings: List[LintFinding] = []
    for path in paths:
        p = Path(path)
        files = sorted(p.rglob("*.py")) if p.is_dir() else [p]
        for f in files:
            findings.extend(lint_file(f, matched))
    if global_checks:
        from .jitlint import allowlist_global_findings

        findings.extend(env_global_findings())
        findings.extend(allowlist_global_findings(matched))
        findings.sort(key=lambda f: (f.path, f.line, f.code))
    return findings
