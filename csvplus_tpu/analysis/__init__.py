"""Static analysis for the device pipeline.

Three layers:

* :mod:`.verify` + :mod:`.schema` — the plan-IR static verifier
  (presence/cardinality/lane/PLACEMENT domains), run by the executor
  before every lowering (``CSVPLUS_VERIFY=0`` disables);
* :mod:`.astlint` — repo-specific AST lint (ctypes boundary, jit
  retrace/trace-churn, eager hot loops, worker purity), run by
  ``make lint`` via ``python -m csvplus_tpu.analysis``;
* :mod:`.report` — the ``--json`` CI payload (lint + example-chain
  verifier reports) snapshot-compared by ``make analyze``.

See docs/ANALYSIS.md for the rule catalogue.
"""

from .astlint import LintFinding, lint_file, lint_paths, lint_source
from .report import json_payload
from .schema import (
    PLACE_DEVICE,
    PLACE_HOST,
    PLACE_UNKNOWN,
    Card,
    ColInfo,
    NodeState,
    Placement,
    Presence,
    placement_of_array,
    placement_of_column,
    sharded_placement,
)
from .verify import (
    EXECUTOR_MODEL,
    Diagnostic,
    ExecutorModel,
    PlanReport,
    verify_before_lower,
    verify_plan,
)

__all__ = [
    "Card",
    "ColInfo",
    "Diagnostic",
    "EXECUTOR_MODEL",
    "ExecutorModel",
    "LintFinding",
    "NodeState",
    "PLACE_DEVICE",
    "PLACE_HOST",
    "PLACE_UNKNOWN",
    "Placement",
    "PlanReport",
    "Presence",
    "json_payload",
    "lint_file",
    "lint_paths",
    "lint_source",
    "placement_of_array",
    "placement_of_column",
    "sharded_placement",
    "verify_before_lower",
    "verify_plan",
]
