"""Static analysis for the device pipeline.

Five layers:

* :mod:`.verify` + :mod:`.schema` — the plan-IR static verifier
  (presence/cardinality/lane/PLACEMENT domains), run by the executor
  before every lowering (``CSVPLUS_VERIFY=0`` disables);
* :mod:`.provenance` + :mod:`.cost` — the rewrite-proving domains:
  per-stage column footprints and shape bits precise enough to PROVE a
  rewrite bitwise-safe, and advisory cardinality/per-placement-bytes
  estimates that rank the candidates;
* :mod:`.rewrite` — the verifier-checked optimizer: applies only
  provenance-proven rewrites, re-verifies, asserts the equivalence
  verdict (``CSVPLUS_OPTIMIZE=0`` disables);
* :mod:`.astlint` — repo-specific AST lint (ctypes boundary, jit
  retrace/trace-churn, eager hot loops, worker purity, lock order), run
  by ``make lint`` via ``python -m csvplus_tpu.analysis``;
* :mod:`.report` — the ``--json`` CI payload (lint + example-chain
  analysis) snapshot-compared by ``make analyze``, and the ``explain``
  CLI's tables.

See docs/ANALYSIS.md for the rule catalogue.
"""

from .astlint import LintFinding, lint_file, lint_paths, lint_source
from .cost import CostEstimate, estimate_plan, rank_join_orders
from .provenance import (
    ProvenanceDiagnostic,
    StageFacts,
    live_columns,
    plan_facts,
    prove_swap_before,
    stage_facts,
)
from .report import json_payload, plan_analysis_json
from .rewrite import (
    PlanRecipe,
    RewriteResult,
    RewriteVerdictMismatch,
    apply_recipe,
    leaf_presence_ok,
    optimize_enabled,
    optimize_plan,
)
from .schema import (
    PLACE_DEVICE,
    PLACE_HOST,
    PLACE_UNKNOWN,
    Card,
    ColInfo,
    NodeState,
    Placement,
    Presence,
    placement_of_array,
    placement_of_column,
    sharded_placement,
)
from .verify import (
    EXECUTOR_MODEL,
    Diagnostic,
    ExecutorModel,
    PlanReport,
    verify_before_lower,
    verify_plan,
)

__all__ = [
    "Card",
    "ColInfo",
    "CostEstimate",
    "Diagnostic",
    "EXECUTOR_MODEL",
    "ExecutorModel",
    "LintFinding",
    "NodeState",
    "PLACE_DEVICE",
    "PLACE_HOST",
    "PLACE_UNKNOWN",
    "Placement",
    "PlanRecipe",
    "PlanReport",
    "Presence",
    "ProvenanceDiagnostic",
    "RewriteResult",
    "RewriteVerdictMismatch",
    "StageFacts",
    "apply_recipe",
    "estimate_plan",
    "json_payload",
    "leaf_presence_ok",
    "lint_file",
    "lint_paths",
    "lint_source",
    "live_columns",
    "optimize_enabled",
    "optimize_plan",
    "plan_analysis_json",
    "plan_facts",
    "placement_of_array",
    "placement_of_column",
    "prove_swap_before",
    "rank_join_orders",
    "sharded_placement",
    "stage_facts",
    "verify_before_lower",
    "verify_plan",
]
