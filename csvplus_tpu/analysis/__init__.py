"""Static analysis for the device pipeline.

Two layers:

* :mod:`.verify` + :mod:`.schema` — the plan-IR static verifier, run by
  the executor before every lowering (``CSVPLUS_VERIFY=0`` disables);
* :mod:`.astlint` — repo-specific AST lint (ctypes boundary, jit
  retrace smells), run by ``make lint`` via ``python -m
  csvplus_tpu.analysis``.

See docs/ANALYSIS.md for the rule catalogue.
"""

from .astlint import LintFinding, lint_file, lint_paths, lint_source
from .schema import Card, ColInfo, NodeState, Presence
from .verify import (
    EXECUTOR_MODEL,
    Diagnostic,
    ExecutorModel,
    PlanReport,
    verify_before_lower,
    verify_plan,
)

__all__ = [
    "Card",
    "ColInfo",
    "Diagnostic",
    "EXECUTOR_MODEL",
    "ExecutorModel",
    "LintFinding",
    "NodeState",
    "PlanReport",
    "Presence",
    "lint_file",
    "lint_paths",
    "lint_source",
    "verify_before_lower",
    "verify_plan",
]
