"""Exhaustive plan-space certification of the rewriter (ISSUE 20).

The rewriter's soundness rested on ~30 hand-picked differential
examples (PRs 16-19).  Per the rewrite-algebra framing (PAPERS.md,
arxiv 2502.06988), soundness should be certified over the plan
*space*: this module enumerates EVERY plan chain up to a size bound
over a small canonical schema, runs ``verify -> optimize`` on each,
and discharges four obligations per plan:

1. **Verdict equality** — re-verifying the rewritten plan must produce
   the same verdict (``ok`` and ``predicts_empty``) as the original;
   a :class:`~csvplus_tpu.analysis.rewrite.RewriteVerdictMismatch` is
   a certification failure, not an exception.
2. **Licensed steps** — every applied recipe step is INDEPENDENTLY
   re-proven here from the provenance primitives
   (:func:`~csvplus_tpu.analysis.provenance.prove_swap_before`,
   :func:`~csvplus_tpu.analysis.provenance.live_columns`, stage
   facts), replaying the recipe one step at a time so each step is
   checked against the exact intermediate chain it addressed.
3. **Bitwise parity** — every plan the rewriter touched executes both
   forms over the seeded corpus: equal positional per-column
   checksums, equal column order, and raising plans must raise the
   SAME exception type on both sides.
4. **Real refusal stages** — every typed refusal
   (:class:`~csvplus_tpu.analysis.provenance.ProvenanceDiagnostic`)
   must name a stage label that exists in the original (or rewritten)
   chain — a refusal naming a phantom stage is a prover bug.

Verifier-rejected trees (unknown columns, key mismatches, ...) are
COUNTED, not crashed — enumerating them is the point: the certifier
proves the optimizer never turns a rejection into an acceptance or
vice versa.

Bounds: ``CSVPLUS_PLANCERT_N`` (default 3) is the max chain size
including the leaf; ``CSVPLUS_PLANCERT_BUDGET_S`` (default 60) is the
wall-clock budget — exceeding it FAILS the run (``make plan-cert``
must stay cheap enough for ``make check``).  The corpus is built once
and memoized; at the default bound the whole space is a few hundred
tiny-table plans.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from .. import plan as P
from ..utils.env import env_float, env_int
from . import provenance as PV
from .rewrite import PlanRecipe, RewriteVerdictMismatch, apply_recipe
from .schema import Presence

__all__ = ["CertSummary", "certify", "summary_json", "DEFAULT_N"]

DEFAULT_N = 3


@dataclass
class CertSummary:
    """Deterministic certification counts (the analyze payload embeds
    these; wall-clock numbers stay OUT so snapshots are stable)."""

    n: int
    budget_s: float
    plans_total: int = 0
    verified_ok: int = 0
    verifier_rejected: int = 0
    predicts_empty: int = 0
    rewritten: int = 0
    executed_pairs: int = 0
    raised_pairs: int = 0
    refusals_checked: int = 0
    failures: List[str] = field(default_factory=list)
    budget_exceeded: bool = False

    @property
    def ok(self) -> bool:
        return not self.failures and not self.budget_exceeded

    def describe(self) -> str:
        lines = [
            f"plan-cert: N={self.n} -> {self.plans_total} plans "
            f"({self.verified_ok} ok, {self.verifier_rejected} rejected, "
            f"{self.predicts_empty} predict-empty)",
            f"  rewritten: {self.rewritten}  executed pairs: "
            f"{self.executed_pairs} ({self.raised_pairs} raising)  "
            f"refusals checked: {self.refusals_checked}",
        ]
        if self.budget_exceeded:
            lines.append(f"  FAILED: budget {self.budget_s}s exceeded")
        for f in self.failures[:20]:
            lines.append(f"  FAILED: {f}")
        if len(self.failures) > 20:
            lines.append(f"  ... and {len(self.failures) - 20} more")
        if self.ok:
            lines.append("  all obligations hold")
        return "\n".join(lines)


def summary_json(s: CertSummary) -> Dict:
    return {
        "n": s.n,
        "plans_total": s.plans_total,
        "verified_ok": s.verified_ok,
        "verifier_rejected": s.verifier_rejected,
        "predicts_empty": s.predicts_empty,
        "rewritten": s.rewritten,
        "executed_pairs": s.executed_pairs,
        "raised_pairs": s.raised_pairs,
        "refusals_checked": s.refusals_checked,
        "failures": list(s.failures),
        "ok": s.ok,
    }


# ---------------------------------------------------------------------------
# Canonical corpus: two leaves, ~a dozen stage constructors.  Memoized —
# the enumeration shares ONE fact table and two build indices, so the
# executor's caches amortize across every plan.

_corpus_cache: List[Tuple] = []


def _corpus():
    if _corpus_cache:
        return _corpus_cache[0]
    import csvplus_tpu as cp
    from ..columnar.table import DeviceTable
    from ..exprs import Rename, SetValue
    from ..predicates import Like

    n = 24
    fact = DeviceTable.from_pylists(
        {
            "id": [str(i % 10) for i in range(n)],
            "cat": [f"k{i % 3}" for i in range(n)],
            "val": [str(i) for i in range(n)],
        },
        device="cpu",
    )
    dim = cp.take(
        DeviceTable.from_pylists(
            # ids 0..7: ids 8/9 of the fact stream MISS -> join narrows
            {"id": [str(i) for i in range(8)],
             "region": [f"r{i % 2}" for i in range(8)]},
            device="cpu",
        )
    ).index_on("id").sync()
    dim2 = cp.take(
        DeviceTable.from_pylists(
            {"cat": ["k0", "k1", "k2"], "label": ["a", "b", "c"]},
            device="cpu",
        )
    ).index_on("cat").sync()

    leaves: List[Tuple[str, Callable[[], P.PlanNode]]] = [
        ("scan", lambda: P.Scan(fact)),
        # a Lookup leaf is a Scan restricted to a contiguous range of a
        # sorted index table (index.py Index.find) — enumerate it too
        ("lookup", lambda: P.Lookup(dim.device_table.table, 1, 6)),
    ]
    stages: List[Tuple[str, Callable[[P.PlanNode], P.PlanNode]]] = [
        ("filter_cat", lambda c: P.Filter(c, Like({"cat": "k1"}))),
        ("filter_id", lambda c: P.Filter(c, Like({"id": "3"}))),
        ("validate", lambda c: P.Validate(c, Like({"cat": "k1"}),
                                          "cert: cat must be k1")),
        ("map_set", lambda c: P.MapExpr(c, SetValue("flag", "x"))),
        ("map_rename", lambda c: P.MapExpr(c, Rename({"val": "v"}))),
        ("select", lambda c: P.SelectCols(c, ("id", "cat"))),
        # valid only downstream of the dim join — most placements are
        # verifier-rejected, which the certifier must COUNT, not crash
        ("select_region", lambda c: P.SelectCols(c, ("region",))),
        ("drop", lambda c: P.DropCols(c, ("val",))),
        ("top", lambda c: P.Top(c, 5)),
        ("join_dim", lambda c: P.Join(c, dim, ("id",))),
        ("join_cat", lambda c: P.Join(c, dim2, ("cat",))),
        ("except_dim", lambda c: P.Except(c, dim, ("id",))),
        ("multiway", lambda c: P.MultiwayJoin(
            c, ((dim, ("id",)), (dim2, ("cat",))))),
    ]
    _corpus_cache.append((leaves, stages))
    return _corpus_cache[0]


def _enumerate_plans(n: int):
    """Every (name, root) chain of size <= n (leaf included), in a
    deterministic order."""
    leaves, stages = _corpus()
    frontier: List[Tuple[str, P.PlanNode]] = [
        (name, mk()) for name, mk in leaves
    ]
    for name, root in frontier:
        yield name, root
    for _ in range(max(n - 1, 0)):
        nxt: List[Tuple[str, P.PlanNode]] = []
        for name, root in frontier:
            for sname, mk in stages:
                plan = (f"{name}>{sname}", mk(root))
                nxt.append(plan)
                yield plan
        frontier = nxt


# ---------------------------------------------------------------------------
# Obligation 2: independent licensing re-check, one recipe step at a
# time against the exact intermediate chain it addressed.


def _presence_fn(facts, leaf_present, upto: int):
    """Stable-presence oracle for the input of chain slot *upto* —
    the same proof the replay-time leaf check re-establishes (see
    rewrite._stable_presence_fn; re-derived here so the certifier does
    not trust the rewriter's own oracle)."""

    def ok(col: str) -> bool:
        if col not in leaf_present:
            return False
        for q in range(1, upto):
            f = facts[q]
            if f.barrier or f.reads is None:
                return False
            if col in f.writes or col in f.removes:
                return False
            if f.keeps_only is not None and col not in f.keeps_only:
                return False
        return True

    return ok


def _check_step(step: Tuple, cur_root: P.PlanNode, leaf_present,
                final_schema) -> List[str]:
    """License one recipe step against the chain it is about to
    rewrite.  Returns human-readable obligation failures."""
    fails: List[str] = []
    chain = P.linearize(cur_root)
    facts = PV.plan_facts(cur_root)
    kind = step[0]
    if kind == "permute":
        slots = list(step[1])
        if sorted(slots) != list(range(len(chain))) or slots[0] != 0:
            return [f"permute {slots} is not a leaf-fixed permutation"]
        # every inversion means some stage moved over another: the
        # moved-up stage must be a narrowing mover and the swap must be
        # provenance-proven against the stage it crossed
        for out_pos, i in enumerate(slots):
            for j in slots[out_pos + 1:]:
                if j >= i:
                    continue
                # original slot i now runs BEFORE original slot j < i
                mover, below = facts[i], facts[j]
                if mover.op not in ("Filter", "Except"):
                    fails.append(
                        f"permute moves non-mover {mover.label}")
                    continue
                d = PV.prove_swap_before(
                    "plan-cert", mover, below,
                    _presence_fn(facts, leaf_present, j),
                )
                if d is not None:
                    fails.append(
                        f"unlicensed swap {mover.label} before "
                        f"{below.label}: {d.message}")
    elif kind == "fuse_joins":
        lo, k = int(step[1]), int(step[2])
        run = chain[lo:lo + k]
        if len(run) != k or not all(isinstance(s, P.Join) for s in run):
            return [f"fuse_joins [{lo},{lo + k}) is not a Join run"]
        # license: every LATER join's key columns must be stably
        # present on the stream side entering the run (the cascade
        # cannot have errored in between)
        ok = _presence_fn(facts, leaf_present, lo)
        for s in run[1:]:
            for col in s.columns:
                if not ok(col):
                    fails.append(
                        f"fuse_joins: key {col!r} of a later join is "
                        "not stably present at the fuse point")
    elif kind == "fuse_chain":
        s0, m = int(step[1]), int(step[2])
        run = chain[s0:s0 + m]
        if len(run) != m or m < 2:
            return [f"fuse_chain [{s0},{s0 + m}) is not a chain run"]
        if not isinstance(run[-1], (P.Join, P.MultiwayJoin)):
            return ["fuse_chain run does not end in a probe"]
        for pos in range(s0, s0 + m - 1):
            f = facts[pos]
            if f.barrier or f.reads is None or not f.row_linear:
                fails.append(
                    f"fuse_chain absorbs {f.label} without a known "
                    "row-linear footprint")
    elif kind == "drop_after_leaf":
        cols = set(step[1])
        live = PV.live_columns(facts, list(final_schema))
        if live is None:
            fails.append("drop_after_leaf with unknown liveness")
        elif cols & live:
            fails.append(
                f"drop_after_leaf drops LIVE columns {sorted(cols & live)}")
    else:
        fails.append(f"unknown recipe step kind {kind!r}")
    return fails


def _check_recipe(root: P.PlanNode, recipe: PlanRecipe, report) -> List[str]:
    leaf_present = frozenset(
        name for name, info in report.states[0].schema.items()
        if info.presence is Presence.PRESENT
    )
    final_schema = list(report.states[-1].schema)
    fails: List[str] = []
    cur = root
    for step in recipe.steps:
        fails.extend(_check_step(step, cur, leaf_present, final_schema))
        try:
            cur = apply_recipe(cur, PlanRecipe(steps=(step,)))
        except ValueError as e:  # malformed step: structural refusal
            fails.append(f"recipe step {step[0]!r} failed to apply: {e}")
            break
    return fails


# ---------------------------------------------------------------------------
# Obligation 3: bitwise differential execution.


def _execute(root: P.PlanNode):
    """("ok", table) | ("raise", exception type name)."""
    from ..columnar.exec import execute_plan_view

    try:
        return ("ok", execute_plan_view(root).materialize())
    except Exception as e:  # noqa: BLE001 — parity compares the TYPE
        return ("raise", type(e).__name__)


def _parity(name: str, original: P.PlanNode,
            rewritten: P.PlanNode) -> Tuple[List[str], bool]:
    from ..utils.checksum import checksum_device_table

    a_kind, a = _execute(original)
    b_kind, b = _execute(rewritten)
    if a_kind != b_kind:
        return ([f"{name}: original {a_kind}({a if a_kind == 'raise' else ''})"
                 f" vs rewritten {b_kind}"
                 f"({b if b_kind == 'raise' else ''})"], False)
    if a_kind == "raise":
        if a != b:
            return ([f"{name}: raises {a} vs {b}"], True)
        return ([], True)
    if a.nrows != b.nrows or list(a.columns) != list(b.columns):
        return ([f"{name}: shape {a.nrows}x{list(a.columns)} vs "
                 f"{b.nrows}x{list(b.columns)}"], False)
    if checksum_device_table(a, positional=True) != checksum_device_table(
            b, positional=True):
        return ([f"{name}: positional checksums differ"], False)
    return ([], False)


# ---------------------------------------------------------------------------


def certify(n: Optional[int] = None,
            budget_s: Optional[float] = None) -> CertSummary:
    """Certify the whole plan space up to size *n* (see module doc)."""
    from .rewrite import optimize_plan
    from .verify import verify_plan

    if n is None:
        n = env_int("CSVPLUS_PLANCERT_N", DEFAULT_N)
    if budget_s is None:
        budget_s = env_float("CSVPLUS_PLANCERT_BUDGET_S", 60.0)
    s = CertSummary(n=n, budget_s=budget_s)
    t0 = time.monotonic()
    for name, root in _enumerate_plans(n):
        if time.monotonic() - t0 > budget_s:
            s.budget_exceeded = True
            break
        s.plans_total += 1
        report = verify_plan(root)
        if report.ok:
            s.verified_ok += 1
        else:
            s.verifier_rejected += 1
        if report.predicts_empty:
            s.predicts_empty += 1

        try:
            result = optimize_plan(root, report)
        except RewriteVerdictMismatch as e:
            s.failures.append(f"{name}: verdict mismatch: {e}")
            continue
        except Exception as e:  # noqa: BLE001 — a crash is a cert failure
            s.failures.append(
                f"{name}: optimize_plan crashed: {type(e).__name__}: {e}")
            continue

        # (1) verdict equality, independently of the rewriter's check
        if (result.report.ok != report.ok
                or result.report.predicts_empty != report.predicts_empty):
            s.failures.append(
                f"{name}: verdict drift ok={report.ok}->"
                f"{result.report.ok} empty={report.predicts_empty}->"
                f"{result.report.predicts_empty}")

        # (4) every typed refusal names a real stage
        labels = {
            P.stage_label(i, nd)
            for i, nd in enumerate(P.linearize(root))
        } | {
            P.stage_label(i, nd)
            for i, nd in enumerate(P.linearize(result.root))
        }
        for d in result.blocked:
            s.refusals_checked += 1
            if d.stage not in labels:
                s.failures.append(
                    f"{name}: refusal names phantom stage {d.stage!r}")

        if not result.recipe:
            continue
        s.rewritten += 1

        # (2) every applied step independently licensed
        s.failures.extend(
            f"{name}: {msg}"
            for msg in _check_recipe(root, result.recipe, report)
        )

        # (3) bitwise parity on every rewritten plan the verifier
        # accepts (rejected plans have no defined execution to compare)
        if report.ok:
            fails, raised = _parity(name, root, result.root)
            s.executed_pairs += 1
            if raised:
                s.raised_pairs += 1
            s.failures.extend(fails)
    return s
