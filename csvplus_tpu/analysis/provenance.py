"""Column-provenance/dependency domain over the plan IR (ISSUE 16).

Per plan stage, this module answers four questions the rewriter and the
views delta-rule gate otherwise each answered with their own ad-hoc
``isinstance`` ladders:

* which columns does the stage READ (their per-row values influence its
  behavior — predicate columns, join/except keys, a select list's
  per-row existence checks);
* which columns does it WRITE (create or overwrite) or REMOVE from the
  schema — everything else passes through with per-row values untouched;
* does it keep row ORDER and row MULTIPLICITY (``preserve`` /
  ``narrow`` / ``expand``), and is each output row produced by one
  input row independently of every other (``row_linear``);
* can it raise a PER-ROW error (``SelectCols``'s host-parity missing
  cell error, ``Join``/``Except``'s key-cell check) or abort the whole
  pipeline (``Validate``)?

Every fact is STRUCTURAL: derived from node types and symbolic
predicate/expr shapes only, never from table data, so the same facts
are exact for any table the plan shape runs over — ``Scan(None)``
included (the views gate checks re-rooted plan shapes before any table
exists).  Two details go beyond flat read/write sets because the
executor's semantics demand them:

* ``keeps_only`` — ``SelectCols`` removes *the complement* of its list,
  which is not expressible as a static remove-set;
* ``fallback_writes`` — ``Join`` merges with stream-wins semantics
  (``ops/join.py``): an index column colliding with a stream column
  overwrites ONLY cells the stream row lacks.  A predicate over such a
  column may only cross the join when the verifier proves the stream
  cells PRESENT.  ``None`` means the index schema is unknown (no device
  table) and nothing may cross.

Consumers: ``analysis/rewrite.py`` (every applied rewrite cites a proof
from this domain; every refusal carries a typed
:class:`ProvenanceDiagnostic` naming the blocking stage) and
``views/rules.py`` (delta-rule eligibility and source-key survival are
provenance facts, defined once here).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from .. import plan as P
from ..exprs import Rename, SetValue, Update
from ..ops.filter import predicate_columns
from ..ops.join import device_index_static_info

__all__ = [
    "ExprFacts",
    "StageFacts",
    "ProvenanceDiagnostic",
    "expr_facts",
    "stage_facts",
    "plan_facts",
    "delta_safe",
    "key_clobbers",
    "live_columns",
    "prove_swap_before",
]

#: Multiplicity verdicts (how output row count relates to input).
PRESERVE = "preserve"
NARROW = "narrow"
EXPAND = "expand"

_EMPTY: frozenset = frozenset()


@dataclass(frozen=True)
class ExprFacts:
    """Read/write/remove footprint of one symbolic Map expr."""

    reads: frozenset
    writes: frozenset
    removes: frozenset
    known: bool  # False: unrecognized expr — assume it may touch anything


def expr_facts(expr) -> ExprFacts:
    """Column footprint of a Map/Transform expr, matching the host
    ``__call__`` semantics in :mod:`csvplus_tpu.exprs` exactly:

    * ``SetValue(c, v)`` writes ``c`` (constant — reads nothing);
    * ``Rename(mapping)`` removes the old names and writes the new ones;
      it also READS both (the executor's merge-with-fallback consults an
      existing column under the new name when the moved one has absent
      cells), so renames never commute with writes to either side;
    * ``Update(*exprs)`` is the sequential union of its parts;
    * anything else is unknown: not a license to rewrite around it.
    """
    if isinstance(expr, SetValue):
        return ExprFacts(_EMPTY, frozenset((expr.column,)), _EMPTY, True)
    if isinstance(expr, Rename):
        olds = frozenset(expr.mapping)
        news = frozenset(expr.mapping.values())
        return ExprFacts(olds | news, news, olds, True)
    if isinstance(expr, Update):
        parts = [expr_facts(e) for e in expr.exprs]
        return ExprFacts(
            frozenset().union(*(p.reads for p in parts)) if parts else _EMPTY,
            frozenset().union(*(p.writes for p in parts)) if parts else _EMPTY,
            frozenset().union(*(p.removes for p in parts)) if parts else _EMPTY,
            all(p.known for p in parts),
        )
    return ExprFacts(_EMPTY, _EMPTY, _EMPTY, False)


@dataclass(frozen=True)
class StageFacts:
    """Structural provenance facts for ONE chain stage."""

    label: str
    op: str
    reads: Optional[frozenset]  # None: unknown (unlowerable pred/expr)
    writes: frozenset = _EMPTY
    removes: frozenset = _EMPTY
    #: SelectCols: only these names survive (complement is removed).
    keeps_only: Optional[frozenset] = None
    #: Join: index columns that fill ONLY absent stream cells
    #: (stream-wins merge).  None: index schema unknown.
    fallback_writes: Optional[frozenset] = _EMPTY
    row_linear: bool = True
    order_preserving: bool = True
    multiplicity: str = PRESERVE
    may_error: bool = False
    aborting: bool = False
    #: Unknown semantics: blocks every rewrite across this stage.
    barrier: bool = False

    @property
    def clobbers(self) -> frozenset:
        """Columns whose per-row values do NOT pass through unchanged
        (written or removed; ``keeps_only`` handled by callers)."""
        return self.writes | self.removes


def _pred_reads(pred) -> Optional[frozenset]:
    cols = predicate_columns(pred)
    return None if cols is None else frozenset(cols)


def stage_facts(pos: int, node: P.PlanNode) -> StageFacts:
    """Provenance facts for chain position *pos* (structural only)."""
    label = P.stage_label(pos, node)
    op = type(node).__name__
    if isinstance(node, (P.Scan, P.Lookup)):
        return StageFacts(label, op, _EMPTY)
    if isinstance(node, P.Filter):
        return StageFacts(label, op, _pred_reads(node.pred),
                          multiplicity=NARROW)
    if isinstance(node, P.Validate):
        # 1:1 passthrough, but aborts mid-stream at the first failing
        # row — no rewrite may change which rows it sees, or when.
        return StageFacts(label, op, _pred_reads(node.pred),
                          may_error=True, aborting=True)
    if isinstance(node, P.MapExpr):
        ef = expr_facts(node.expr)
        if not ef.known:
            return StageFacts(label, op, None, barrier=True)
        return StageFacts(label, op, ef.reads, writes=ef.writes,
                          removes=ef.removes)
    if isinstance(node, P.SelectCols):
        # Per-row existence check with host-parity errors: the executor
        # raises at the FIRST streamed row lacking a selected cell, so
        # the select list is read, not just projected.
        keep = frozenset(node.columns)
        return StageFacts(label, op, keep, keeps_only=keep, may_error=True)
    if isinstance(node, P.DropCols):
        # Pure dict filter, no error semantics (metadata only).
        return StageFacts(label, op, _EMPTY,
                          removes=frozenset(node.columns))
    if isinstance(node, (P.Top, P.DropRows)):
        return StageFacts(label, op, _EMPTY, row_linear=False,
                          multiplicity=NARROW)
    if isinstance(node, (P.TakeWhile, P.DropWhile)):
        # Prefix-dependent: a row's visibility depends on EARLIER rows.
        return StageFacts(label, op, _pred_reads(node.pred),
                          row_linear=False, multiplicity=NARROW)
    if isinstance(node, (P.Join, P.Except)):
        keys = frozenset(node.columns)
        if isinstance(node, P.Except):
            # Anti-join: narrows the selection, adds no columns.
            return StageFacts(label, op, keys, multiplicity=NARROW,
                              may_error=True)
        info = device_index_static_info(node.index)
        if info is None or not info[2]:
            fallback: Optional[frozenset] = None  # index schema unknown
        else:
            fallback = frozenset(info[0]) - keys
        # Key columns are NOT writes: every surviving row had its key
        # cells present (``_check_key_cells`` errors otherwise — the
        # ``may_error`` obligation makes any proof across this stage
        # demand proven key presence), and the matched values are the
        # stream's own, so key values pass through bitwise.
        return StageFacts(label, op, keys,
                          fallback_writes=fallback, multiplicity=EXPAND,
                          may_error=True)
    if isinstance(node, P.MultiwayJoin):
        # The fused operator inherits the cascade's facts dimension-wise:
        # it reads every dimension's keys, and a column may be filled
        # from ANY build side whose schema carries it as a non-key (the
        # per-dimension stream-wins merges compose left to right, so the
        # union of the per-join fallback sets is the sound fused set).
        # Key pass-through is identical to the cascade: every surviving
        # row had ALL key cells present, values bitwise the stream's own.
        keys = frozenset().union(
            *(frozenset(cols) for _idx, cols in node.joins)
        )
        fallback: Optional[frozenset] = _EMPTY
        for idx, cols in node.joins:
            info = device_index_static_info(idx)
            if info is None or not info[2]:
                fallback = None  # a build-side schema is unknown
                break
            fallback = fallback | (frozenset(info[0]) - frozenset(cols))
        return StageFacts(label, op, keys,
                          fallback_writes=fallback, multiplicity=EXPAND,
                          may_error=True)
    if isinstance(node, P.FusedProbe):
        # The fused probe pass (ISSUE 19) composes its absorbed ops'
        # facts via ``fused_op_node`` — each op contributes exactly what
        # its standalone stage would, BY CONSTRUCTION — then folds the
        # probe dimensions like MultiwayJoin.  ``keeps_only`` intersects
        # the absorbed selects (sound over-approximation: the true kept
        # set is the last select's list minus later removes, a subset of
        # the intersection's complement's complement — every consumer of
        # ``keeps_only`` treats it as "at most these survive").
        reads: set = set()
        writes: set = set()
        removes: set = set()
        keeps_only: Optional[frozenset] = None
        may_error = False
        for kind, payload in node.ops:
            sub = P.fused_op_node(kind, payload)
            if sub is None:
                return StageFacts(label, op, None, row_linear=False,
                                  order_preserving=False, barrier=True)
            f = stage_facts(pos, sub)
            if f.barrier or f.reads is None:
                return StageFacts(label, op, None, row_linear=False,
                                  order_preserving=False, barrier=True)
            reads |= f.reads
            writes |= f.writes
            removes |= f.removes
            if f.keeps_only is not None:
                keeps_only = (
                    f.keeps_only if keeps_only is None
                    else keeps_only & f.keeps_only
                )
            may_error = may_error or f.may_error
        keys = frozenset().union(
            *(frozenset(cols) for _idx, cols in node.joins)
        )
        reads |= keys
        fallback: Optional[frozenset] = _EMPTY
        for idx, cols in node.joins:
            info = device_index_static_info(idx)
            if info is None or not info[2]:
                fallback = None  # a build-side schema is unknown
                break
            fallback = fallback | (frozenset(info[0]) - frozenset(cols))
        return StageFacts(label, op, frozenset(reads),
                          writes=frozenset(writes),
                          removes=frozenset(removes),
                          keeps_only=keeps_only,
                          fallback_writes=fallback, multiplicity=EXPAND,
                          may_error=True)
    # Unknown node type: total barrier — and no row-linearity claim.
    return StageFacts(label, op, None, row_linear=False,
                      order_preserving=False, barrier=True)


def plan_facts(root: P.PlanNode) -> List[StageFacts]:
    """Facts for every :func:`~csvplus_tpu.plan.linearize` slot of *root*."""
    return [stage_facts(i, n) for i, n in enumerate(P.linearize(root))]


# ---------------------------------------------------------------------------
# Delta-rule facts (consumed by views/rules.py)


def delta_safe(facts: StageFacts) -> bool:
    """Does the stage admit a per-tier delta rule?  Exactly the
    row-linear + order-preserving + non-aborting ops of the bag-algebra
    (views/rules.py module docstring) — ``Filter``/``MapExpr``/
    ``SelectCols``/``DropCols``/``Join``/``Except`` qualify; positional
    windows and ``Validate`` do not.  (A Map with an unknown expr still
    returns True here: the delta gate rejects it at the key-survival
    level with its own diagnostic.)"""
    return facts.row_linear and not facts.aborting


def key_clobbers(facts: StageFacts,
                 key_columns: Sequence[str]) -> Tuple[List[str], List[str]]:
    """Which source key columns this stage fails to carry through:
    ``(clobbered_by_write_or_remove, projected_away)``.  Join's
    ``fallback_writes``/key writes do not count — the matched key VALUES
    are the stream's own, so retraction-by-key still addresses the same
    rows (matching the historical gate's behavior)."""
    keys = list(key_columns)
    if facts.op in ("Join", "Except", "MultiwayJoin"):
        return ([], [])
    clobbered = [k for k in keys if k in facts.clobbers]
    projected = []
    if facts.keeps_only is not None:
        projected = [k for k in keys
                     if k not in facts.keeps_only and k not in clobbered]
    return (clobbered, projected)


# ---------------------------------------------------------------------------
# Rewrite proofs


@dataclass(frozen=True)
class ProvenanceDiagnostic:
    """A typed refusal: why a rewrite is NOT provenance-proven, naming
    the blocking stage by its canonical ``Type[pos]`` label."""

    rule: str  # e.g. "predicate-pushdown"
    stage: str  # blocking stage label
    message: str

    def __str__(self) -> str:
        return f"{self.rule}: blocked by {self.stage}: {self.message}"


def _present(presence_ok, cols) -> bool:
    """True when *presence_ok* proves every column in *cols* PRESENT at
    the relevant position; ``presence_ok`` is a callable injected by the
    rewriter (closed over the verifier's abstract states)."""
    return all(presence_ok(c) for c in cols)


def prove_swap_before(
    rule: str,
    mover: StageFacts,
    below: StageFacts,
    presence_below_in,
) -> Optional[ProvenanceDiagnostic]:
    """Prove that a row-NARROWING stage *mover* (Filter or Except) may
    move from directly after *below* to directly before it, bitwise.

    *presence_below_in(col)* must return True only when the verifier
    proves *col* PRESENT in every row entering *below* — the input the
    mover would run over after the swap.

    The proof obligations, each tied to executor semantics
    (``columnar/exec.py`` / ``ops/join.py``):

    * *below* has known semantics and is row-linear + order-preserving
      (positional windows change meaning if the row set changes first;
      Validate's abort position is observable);
    * the mover's read columns are not written/removed/projected by
      *below* — their per-row values are identical on either side;
    * read columns in *below*'s ``fallback_writes`` (Join stream-wins
      merge) additionally need PRESENT stream cells, else the join
      would have filled them from the index after the mover ran;
    * *below*'s own per-row error, if any, must be impossible
      (its read columns PRESENT): narrowing first could skip the row
      that errored, changing observable behavior;
    * if the mover itself checks key cells (Except), those must be
      PRESENT at the swapped position: rows *below* would have
      removed/never-produced could otherwise trip the check.
    """

    def blocked(msg: str) -> ProvenanceDiagnostic:
        return ProvenanceDiagnostic(rule, below.label, msg)

    if below.barrier:
        return blocked(f"{below.op} has unknown semantics")
    if not below.row_linear or not below.order_preserving:
        return blocked(
            f"{below.op} is positional/prefix-dependent — narrowing the "
            f"row set first changes which rows it keeps")
    if below.aborting:
        return blocked(
            f"{below.op} aborts at the first failing row — narrowing "
            f"first can move or suppress the abort")
    if mover.reads is None:
        return ProvenanceDiagnostic(
            rule, mover.label,
            f"{mover.op} reads an unlowerable predicate — its column "
            f"footprint is unknown")
    hit = mover.reads & below.clobbers
    if hit:
        return blocked(
            f"{below.op} writes/removes {sorted(hit)} which the "
            f"{mover.op} predicate reads")
    if below.keeps_only is not None:
        outside = mover.reads - below.keeps_only
        if outside:
            return blocked(
                f"{below.op} projects away {sorted(outside)} which the "
                f"{mover.op} predicate reads")
    if below.fallback_writes is None:
        return blocked(f"{below.op} build-side schema is unknown")
    shadow = mover.reads & below.fallback_writes
    if shadow and not _present(presence_below_in, shadow):
        return blocked(
            f"{below.op} may fill absent cells of {sorted(shadow)} from "
            f"its build side (stream-wins merge); stream presence is "
            f"not proven")
    if below.may_error and below.reads is not None:
        if not _present(presence_below_in, below.reads):
            return blocked(
                f"{below.op} raises per-row errors on missing "
                f"{sorted(below.reads)} cells; presence is not proven, "
                f"so narrowing first could suppress or reorder the error")
    if mover.may_error and mover.reads:
        if not _present(presence_below_in, mover.reads):
            return ProvenanceDiagnostic(
                rule, mover.label,
                f"{mover.op} checks {sorted(mover.reads)} cells per row; "
                f"presence at the earlier position is not proven")
    return None


def live_columns(facts: Sequence[StageFacts],
                 final_schema: Sequence[str]) -> Optional[frozenset]:
    """The set of leaf columns that can influence execution or output:
    every column any stage reads or writes, plus the final output
    schema.  A leaf column OUTSIDE this set is dead — no stage's
    behavior (including per-row error checks, which only consult read
    columns) or result can depend on it, so dropping it at the Scan is
    bitwise-invisible.  Written columns are kept too: overwriting an
    existing column preserves its schema position, while recreating a
    dropped one appends at the end.  Returns ``None`` when any stage
    has an unknown footprint (no liveness claim is sound)."""
    live = set(final_schema)
    for f in facts:
        if f.barrier or f.reads is None:
            return None
        live |= f.reads | f.writes
        if f.fallback_writes is None and f.op in (
            "Join", "MultiwayJoin", "FusedProbe"
        ):
            return None
    return frozenset(live)
