// Go-class CPU proxy for the reference's 3-way lookup join hot loop.
//
// Mirrors csvplus.go:552-583 per stream row: two binary searches over
// sorted key arrays (sort.Search with per-key string compares,
// csvplus.go:869-920) and two map merges into a freshly allocated row
// map (mergeRows, csvplus.go:571-583), rows as string->string hash maps
// (Go's map[string]string).  Compiled C++ is the same performance class
// as compiled Go on this shape — hash-map churn and string compares
// dominate — so its rows/s bounds the "vs Go" multiple honestly where
// no Go toolchain exists (BASELINE.md metric definition).
//
// Usage: bench_oracle orders.csv customers.csv products.csv
// Output: one line "<rows_per_sec>" (join loop only; IO/build excluded).

#include <algorithm>
#include <chrono>
#include <cstdio>
#include <fstream>
#include <sstream>
#include <string>
#include <unordered_map>
#include <vector>

using Row = std::unordered_map<std::string, std::string>;

static std::vector<std::string> split(const std::string& line) {
  std::vector<std::string> out;
  size_t start = 0;
  for (;;) {
    size_t pos = line.find(',', start);
    if (pos == std::string::npos) {
      out.push_back(line.substr(start));
      return out;
    }
    out.push_back(line.substr(start, pos - start));
    start = pos + 1;
  }
}

static bool read_csv(const char* path, std::vector<std::string>& header,
                     std::vector<std::vector<std::string>>& rows) {
  std::ifstream f(path);
  if (!f) return false;
  std::string line;
  if (!std::getline(f, line)) return false;
  header = split(line);
  while (std::getline(f, line)) {
    if (!line.empty()) rows.push_back(split(line));
  }
  return true;
}

// build side: rows sorted by one key column, searched like
// indexImpl.find (two sort.Search calls -> lower bound on unique keys)
struct Index {
  std::vector<std::pair<std::string, Row>> rows;  // sorted by key
  void build(const std::vector<std::string>& header,
             std::vector<std::vector<std::string>>& data, const std::string& key) {
    size_t ki = 0;
    for (size_t i = 0; i < header.size(); ++i)
      if (header[i] == key) ki = i;
    rows.reserve(data.size());
    for (auto& rec : data) {
      Row r;
      for (size_t i = 0; i < header.size() && i < rec.size(); ++i)
        r.emplace(header[i], std::move(rec[i]));
      rows.emplace_back(r.at(key), std::move(r));
    }
    std::sort(rows.begin(), rows.end(),
              [](const auto& a, const auto& b) { return a.first < b.first; });
  }
  const Row* find(const std::string& v) const {
    auto it = std::lower_bound(
        rows.begin(), rows.end(), v,
        [](const auto& a, const std::string& key) { return a.first < key; });
    if (it == rows.end() || it->first != v) return nullptr;
    return &it->second;
  }
};

int main(int argc, char** argv) {
  if (argc != 4) return 2;
  std::vector<std::string> oh, ch, ph;
  std::vector<std::vector<std::string>> orows, crows, prows;
  if (!read_csv(argv[1], oh, orows) || !read_csv(argv[2], ch, crows) ||
      !read_csv(argv[3], ph, prows))
    return 3;
  Index cust, prod;
  cust.build(ch, crows, "id");
  prod.build(ph, prows, "prod_id");

  size_t cust_i = 0, prod_i = 0;
  for (size_t i = 0; i < oh.size(); ++i) {
    if (oh[i] == "cust_id") cust_i = i;
    if (oh[i] == "prod_id") prod_i = i;
  }

  const auto t0 = std::chrono::steady_clock::now();
  uint64_t matched = 0;
  for (const auto& rec : orows) {
    // stream row materializes as a map per record, like the reference's
    // Reader.Iterate (csvplus.go:1118-1131)
    Row stream;
    for (size_t i = 0; i < oh.size() && i < rec.size(); ++i)
      stream.emplace(oh[i], rec[i]);
    const Row* c = cust.find(rec[cust_i]);
    if (!c) continue;
    Row merged = *c;  // mergeRows: index row copies first...
    for (const auto& kv : stream) merged[kv.first] = kv.second;  // stream wins
    const Row* p = prod.find(rec[prod_i]);
    if (!p) continue;
    Row merged2 = *p;
    for (const auto& kv : merged) merged2[kv.first] = kv.second;
    matched += merged2.size() >= stream.size();
  }
  const double dt =
      std::chrono::duration<double>(std::chrono::steady_clock::now() - t0)
          .count();
  std::printf("%.1f %llu\n", orows.size() / dt, (unsigned long long)matched);
  return 0;
}
