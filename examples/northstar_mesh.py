"""Mesh-scale north-star run: the 3-way join end-to-end SHARDED.

VERDICT r4 next #4: the at-scale record must exist for the MESH path,
not just single-device — sharded streamed ingest (chunks land on their
shard, ingest.py `_finalize_sharded`) → broadcast joins over the
row-sharded stream → per-column checksum parity vs the host executor,
with per-stage wall times and placement evidence in the JSON.

Runs on the virtual 8-device CPU mesh
(``XLA_FLAGS=--xla_force_host_platform_device_count=8``) — re-execs
itself into that environment if the current process lacks 8 devices.

r06: headline join rates are measured with telemetry DISABLED (so the
collector's per-stage barriers can't perturb them), then one extra
warm-join pass runs with telemetry ENABLED to produce the per-stage
attribution table (join:translate/pack/probe/expand/merge, plus
partition/all_to_all when that tier engages) that the artifact
carries.  Ingest telemetry (ingest:scan/place/seal/shard-assemble) is
collected during the single streaming-ingest pass itself — its
accounting is pure perf_counter accumulation, no barriers.

Usage: python examples/northstar_mesh.py [n_orders]   (default 10M)
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N_SHARDS = 8


def _ensure_mesh_env() -> None:
    """Re-exec into a hermetic 8-device CPU environment when needed."""
    if os.environ.get("NORTHSTAR_MESH_HERMETIC") == "1":
        return
    env = dict(os.environ)
    env["NORTHSTAR_MESH_HERMETIC"] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={N_SHARDS}"
        ).strip()
    env.pop("PALLAS_AXON_POOL_IPS", None)
    os.execve(sys.executable, [sys.executable] + sys.argv, env)


def _rss_mb() -> float:
    from csvplus_tpu.obs.memory import peak_rss_mb

    return peak_rss_mb()


def main() -> None:
    _ensure_mesh_env()
    # the sharded-ingest path lives in the streamed tier; engage it at
    # any file size for this run (recorded in the JSON)
    os.environ.setdefault("CSVPLUS_STREAM_MIN_BYTES", "1")

    args = [a for a in sys.argv[1:] if not a.startswith("--")]
    n_orders = int(args[0]) if args else 10_000_000
    if "--skew" in sys.argv:
        _skew_main(n_orders)
        return
    if "--multiway" in sys.argv:
        _multiway_main(n_orders)
        return
    from northstar import DATA_DIR, generate  # same generator/cache

    opath = generate(n_orders)
    print(
        f"orders file: {opath} ({os.path.getsize(opath) / 1e9:.2f} GB)",
        file=sys.stderr,
    )

    import jax

    from csvplus_tpu import FromFile, Take
    from csvplus_tpu.native.scanner import _ingest_workers
    from csvplus_tpu.obs.memory import host_header
    from csvplus_tpu.utils.observe import telemetry

    assert len(jax.devices()) >= N_SHARDS, jax.devices()

    t0 = time.perf_counter()
    with telemetry.collect() as records:
        orders = FromFile(opath).OnDevice(shards=N_SHARDS)
        orders.plan.table.sync()
    t_ingest = time.perf_counter() - t0
    table = orders.plan.table
    # the collector's record list is reset in place by the next
    # ``collect()`` — copy the ingest stages out first
    ingest_records = list(records)
    assemble = next(
        (r for r in ingest_records if r.stage == "ingest:shard-assemble"), None
    )
    pre_sharded = bool(getattr(table, "_pre_sharded", False))
    shard_counts = {
        name: len(col.storage.sharding.device_set)
        for name, col in table.columns.items()
    }
    print(
        f"ingest (sharded): {n_orders / t_ingest:,.0f} rows/s ({t_ingest:,.1f}s),"
        f" pre_sharded={pre_sharded}, per-column shard counts={shard_counts},"
        f" rss {_rss_mb():,.0f} MB",
        file=sys.stderr,
    )
    assert pre_sharded, "sharded ingest did not engage"
    assert all(v == N_SHARDS for v in shard_counts.values()), shard_counts

    t0 = time.perf_counter()
    cust_idx = (
        FromFile(os.path.join(DATA_DIR, "customers.csv")).OnDevice().UniqueIndexOn("id")
    )
    prod_idx = (
        FromFile(os.path.join(DATA_DIR, "products.csv"))
        .OnDevice()
        .UniqueIndexOn("prod_id")
    )
    t_index = time.perf_counter() - t0
    print(f"index build: {t_index:,.1f}s", file=sys.stderr)

    joined = orders.Join(cust_idx, "cust_id").Join(prod_idx)
    t0 = time.perf_counter()
    result = joined.to_device_table().sync()
    t_join = time.perf_counter() - t0
    assert result.nrows == n_orders, result.nrows
    print(
        f"3-way join (sharded stream, broadcast build): "
        f"{n_orders / t_join:,.0f} rows/s ({t_join:,.2f}s)",
        file=sys.stderr,
    )
    # steady-state warm rate: best of 3 passes, the previous pass's
    # result RELEASED first so XLA reuses its buffers (at 100M rows a
    # retained 3.2GB result forces every warm pass to fault in a fresh
    # copy and dominates the measurement with page faults, not join
    # work; bench.py's reps contract likewise holds no extra result).
    # The verification copy is re-materialized afterwards.
    result = None
    from csvplus_tpu.obs.recompile import RecompileWatch

    warm_times = []
    # warm passes must lower NOTHING: every registered kernel's jit
    # cache is snapshotted before and asserted unchanged after (the r05
    # regression was exactly warm-path eager/retrace work)
    with RecompileWatch() as recompiles:
        for _ in range(3):
            t0 = time.perf_counter()
            r = joined.to_device_table().sync()
            warm_times.append(time.perf_counter() - t0)
            r = None
    recompiles.assert_zero("mesh warm joins")
    t_warm = min(warm_times)
    print(
        f"3-way join (warm, best of {len(warm_times)}):"
        f" {n_orders / t_warm:,.0f} rows/s ({t_warm:,.2f}s;"
        f" passes {', '.join(f'{t:,.2f}s' for t in warm_times)});"
        f" rss {_rss_mb():,.0f} MB",
        file=sys.stderr,
    )

    # ---- per-stage attribution table (r06): one extra warm pass with
    # telemetry enabled.  Its per-stage barriers serialize dispatch, so
    # this pass is NOT the headline number — it is the breakdown that
    # says where the wall time goes. ----
    t0 = time.perf_counter()
    with telemetry.collect() as jrecords:
        joined.to_device_table().sync()
        join_records = list(jrecords)
    t_instrumented = time.perf_counter() - t0
    telemetry.records[:] = ingest_records + join_records
    stage_table = telemetry.to_json()["stage_table"]
    telemetry.reset()
    print(
        f"3-way join (instrumented warm pass): {t_instrumented:,.2f}s;"
        " per-stage table:",
        file=sys.stderr,
    )
    for row in stage_table:
        print(f"  {row}", file=sys.stderr)
    print(f"rss after timed joins: {_rss_mb():,.0f} MB", file=sys.stderr)

    # ---- verification: positional checksums vs the host executor on a
    # 1M-row prefix + full-result checksums for cross-run comparison.
    # Host side FIRST: the 1M-row host join holds ~2GB of Row dicts, so
    # it runs (and is released) before the device verification copy is
    # re-materialized — the two memory peaks must not overlap. ----
    from csvplus_tpu import StopPipeline, take_rows
    from csvplus_tpu.utils.checksum import (
        checksum_device_table,
        checksum_host_rows,
    )

    sample = min(1_000_000, n_orders)
    head: list = []

    def collect(row):
        head.append(row)
        if len(head) >= sample:
            raise StopPipeline

    Take(FromFile(opath))(collect)
    h_cust = Take(FromFile(os.path.join(DATA_DIR, "customers.csv"))).UniqueIndexOn("id")
    h_prod = Take(FromFile(os.path.join(DATA_DIR, "products.csv"))).UniqueIndexOn(
        "prod_id"
    )
    t0 = time.perf_counter()
    host_rows = take_rows(head).Join(h_cust, "cust_id").Join(h_prod).to_rows()
    cols = sorted(host_rows[0].header()) if host_rows else []
    want = checksum_host_rows(host_rows, cols, positional=True)
    head.clear()
    host_rows = None
    # the oracle's ~2GB of Row dicts are freed but allocator-retained;
    # return them to the OS before the device verification copy and the
    # checksum transients stack on top of that base
    from csvplus_tpu.columnar.ingest import _trim_host_staging

    _trim_host_staging()
    print(f"rss after host oracle join: {_rss_mb():,.0f} MB", file=sys.stderr)

    # the verification copy (released before the warm passes above)
    result = joined.to_device_table().sync()
    assert result.nrows == n_orders, result.nrows
    assert sorted(result.columns) == cols, (sorted(result.columns), cols)
    got = checksum_device_table(result, cols, limit=sample, positional=True)
    assert got == want, f"checksum mismatch over the first {sample} rows"
    t_verify = time.perf_counter() - t0
    print(
        f"parity: positional checksums over the first {sample:,} rows match"
        f" the host executor ({t_verify:,.1f}s)",
        file=sys.stderr,
    )
    _trim_host_staging()  # parity-pass leftovers, before the peak phase
    full_sums = checksum_device_table(result, cols, positional=True)
    print(f"rss after full checksums: {_rss_mb():,.0f} MB", file=sys.stderr)

    print(
        json.dumps(
            {
                "metric": "northstar_mesh_threeway_join",
                "rows": n_orders,
                "n_shards": N_SHARDS,
                "ingest_workers": _ingest_workers(),
                "backend": jax.default_backend(),
                **host_header(),
                "recompiles_warm": recompiles.delta(),
                "recompiles_observable": recompiles.observable(),
                "ingest_rows_per_sec": round(n_orders / t_ingest, 1),
                "join_rows_per_sec": round(n_orders / t_join, 1),
                "join_rows_per_sec_warm": round(n_orders / t_warm, 1),
                "end_to_end_sec": round(t_ingest + t_index + t_join, 1),
                "peak_host_rss_mb": round(_rss_mb(), 1),
                "pre_sharded_ingest": pre_sharded,
                "max_shard_rows": assemble.extra.get("max_shard_rows")
                if assemble
                else None,
                "column_shard_counts": shard_counts,
                "parity_checked_rows": sample,
                "full_result_checksums": full_sums,
                "instrumented_warm_sec": round(t_instrumented, 2),
                "stage_table": stage_table,
                "note": (
                    "virtual 8-device CPU mesh: rates measure the sharded "
                    "EXECUTION PATH (placement, collectives, assembly), not "
                    "chip throughput; chunks land on their shard at ingest "
                    "(typed columns seal per shard as the scan passes them — "
                    "no full-table single-device buffer) and the joins run "
                    "broadcast over the row-sharded stream; stage_table is "
                    "from one extra warm pass with telemetry barriers on, "
                    "headline rates are telemetry-off"
                ),
                "history": {
                    "pre_fused": {
                        "ingest_rows_per_sec": 2719144.7,
                        "join_rows_per_sec_warm": 15081187.1,
                    },
                    "r05_fused_ingest": {
                        "rows": 10_000_000,
                        "ingest_rows_per_sec": 4193327.1,
                        "join_rows_per_sec_warm": 13895781.1,
                        "diagnosis": (
                            "warm-join regression vs pre_fused DIAGNOSED "
                            "(r06, was flagged unexplained): the fused typed "
                            "ingest switched probe keys to typed int lanes "
                            "whose per-execution value->code translation ran "
                            "as ~6 unfused eager passes per key column, plus "
                            "an eager per-column query-key pack loop; fixed "
                            "by module-level jitted kernels "
                            "(columnar/typed.py _translate_*_kernel, "
                            "ops/join.py _pack_qk_kernel, columnar/table.py "
                            "_apply_code_translation) — see ROADMAP.md "
                            "decision note"
                        ),
                    },
                },
            }
        )
    )


def _skew_main(n_orders: int) -> None:
    """The ``--skew`` tier (ISSUE 15): the 3-way join over a Zipf-skewed
    orders stream, skew-aware vs skew-naive IN THE SAME RUN.

    Same measurement discipline as the uniform tier — cold pass, warm
    best-of-3 with telemetry off and zero recompiles asserted, then one
    instrumented pass for the per-stage table — executed twice: once
    with ``CSVPLUS_JOIN_SKEW=0`` (hash-repartition only) and once with
    the skew tier on.  Both legs see identical bytes, and the artifact
    carries bitwise parity (full positional per-column checksums, not a
    prefix) plus the routing counters that say how many rows the
    broadcast tier absorbed.
    """
    # the partition tier must engage on the 1.5M-key customer index
    # (class attr is read when ops/join.py is imported — set first),
    # and the detection sample/threshold are sized for a 1.1-exponent
    # tail where single keys hold only ~0.1-12% each: a 1/(2n) default
    # threshold would catch the top couple of keys, which shrinks the
    # exchange barely at all.  All overrides land in the artifact.
    os.environ.setdefault("CSVPLUS_PARTITION_MIN_KEYS", "1000000")
    os.environ.setdefault("CSVPLUS_JOIN_SKEW_SAMPLE", "16384")
    os.environ.setdefault("CSVPLUS_JOIN_SKEW_THRESHOLD", "0.002")
    n_cust = int(os.environ.get("CSVPLUS_BENCH_MESH_ZIPF_CUSTOMERS", 1_500_000))
    zipf_s = float(os.environ.get("CSVPLUS_BENCH_MESH_ZIPF_S", 1.1))

    import bench  # repo root is on sys.path (header insert)

    opath, cpath = bench.zipf_fact_table(n_orders, n_cust, s=zipf_s)
    print(
        f"zipf orders file: {opath} ({os.path.getsize(opath) / 1e9:.2f} GB),"
        f" s={zipf_s}, {n_cust:,} customers",
        file=sys.stderr,
    )

    import jax

    from csvplus_tpu import FromFile
    from csvplus_tpu.native.scanner import _ingest_workers
    from csvplus_tpu.obs.joinskew import joinskew
    from csvplus_tpu.obs.memory import host_header
    from csvplus_tpu.obs.recompile import RecompileWatch
    from csvplus_tpu.utils.checksum import checksum_device_table
    from csvplus_tpu.utils.observe import telemetry

    assert len(jax.devices()) >= N_SHARDS, jax.devices()

    t0 = time.perf_counter()
    orders = FromFile(opath).OnDevice(shards=N_SHARDS)
    orders.plan.table.sync()
    t_ingest = time.perf_counter() - t0
    table = orders.plan.table
    assert getattr(table, "_pre_sharded", False), "sharded ingest did not engage"
    shard_rows = table.shard_row_counts()
    print(
        f"ingest (sharded): {n_orders / t_ingest:,.0f} rows/s"
        f" ({t_ingest:,.1f}s), shard rows={shard_rows},"
        f" rss {_rss_mb():,.0f} MB",
        file=sys.stderr,
    )

    from northstar import DATA_DIR  # products.csv lives in the same cache

    t0 = time.perf_counter()
    cust_idx = FromFile(cpath).OnDevice().UniqueIndexOn("id")
    prod_idx = (
        FromFile(os.path.join(DATA_DIR, "products.csv"))
        .OnDevice()
        .UniqueIndexOn("prod_id")
    )
    t_index = time.perf_counter() - t0
    print(f"index build: {t_index:,.1f}s", file=sys.stderr)

    joined = orders.Join(cust_idx, "cust_id").Join(prod_idx)
    joinskew.reset()

    legs = {}
    stage_tables = {}
    checksums = {}
    for mode, flag in (("naive", "0"), ("skew", "1")):
        os.environ["CSVPLUS_JOIN_SKEW"] = flag
        t0 = time.perf_counter()
        result = joined.to_device_table().sync()
        t_cold = time.perf_counter() - t0
        assert result.nrows == n_orders, result.nrows
        cols = sorted(result.columns)
        checksums[mode] = checksum_device_table(result, cols, positional=True)
        result = None  # release before the warm passes (see main())
        warm_times = []
        with RecompileWatch() as recompiles:
            for _ in range(3):
                t0 = time.perf_counter()
                r = joined.to_device_table().sync()
                warm_times.append(time.perf_counter() - t0)
                r = None
        recompiles.assert_zero(f"mesh warm zipf joins ({mode})")
        t_warm = min(warm_times)
        with telemetry.collect() as jrecords:
            joined.to_device_table().sync()
            join_records = list(jrecords)
        telemetry.records[:] = join_records
        stage_tables[mode] = telemetry.to_json()["stage_table"]
        telemetry.reset()
        legs[mode] = {
            "cold_sec": round(t_cold, 2),
            "warm_sec": round(t_warm, 2),
            "warm_passes_sec": [round(t, 2) for t in warm_times],
            "rows_per_sec_warm": round(n_orders / t_warm, 1),
            "recompiles_warm": recompiles.delta(),
        }
        print(
            f"3-way zipf join [{mode}]: warm best-of-3"
            f" {n_orders / t_warm:,.0f} rows/s ({t_warm:,.2f}s; passes"
            f" {', '.join(f'{t:,.2f}s' for t in warm_times)});"
            f" rss {_rss_mb():,.0f} MB",
            file=sys.stderr,
        )

    assert checksums["skew"] == checksums["naive"], (
        "bitwise parity broke: skew-aware checksums differ from the"
        " CSVPLUS_JOIN_SKEW=0 run"
    )
    # counters are labelled by the INDEX key columns ("id" for the
    # customer dimension), not the probe-side column name
    snap = joinskew.counters_snapshot()
    counters = snap.get("id")
    assert counters and counters["hot_keys_detected"] > 0, (
        f"skew tier never engaged on the Zipf stream: {snap}"
    )
    speedup = legs["naive"]["warm_sec"] / legs["skew"]["warm_sec"]
    print(
        f"parity: full positional checksums identical across modes;"
        f" skew routing: {counters}; speedup {speedup:,.2f}x",
        file=sys.stderr,
    )

    print(
        json.dumps(
            {
                "metric": "northstar_mesh_threeway_join_zipf",
                "rows": n_orders,
                "n_shards": N_SHARDS,
                "n_customers": n_cust,
                "zipf_s": zipf_s,
                "ingest_workers": _ingest_workers(),
                "backend": jax.default_backend(),
                **host_header(),
                "env_overrides": {
                    k: os.environ[k]
                    for k in (
                        "CSVPLUS_PARTITION_MIN_KEYS",
                        "CSVPLUS_JOIN_SKEW_SAMPLE",
                        "CSVPLUS_JOIN_SKEW_THRESHOLD",
                        "CSVPLUS_STREAM_MIN_BYTES",
                    )
                },
                "ingest_rows_per_sec": round(n_orders / t_ingest, 1),
                "join_rows_per_sec_warm_zipf": legs["skew"]["rows_per_sec_warm"],
                "join_rows_per_sec_warm_naive": legs["naive"]["rows_per_sec_warm"],
                "skew_speedup": round(speedup, 2),
                "legs": legs,
                "skew_counters": counters,
                "parity_bitwise": True,
                "full_result_checksums": checksums["skew"],
                "shard_rows": shard_rows,
                "peak_host_rss_mb": round(_rss_mb(), 1),
                "stage_table_naive": stage_tables["naive"],
                "stage_table_skew": stage_tables["skew"],
                "note": (
                    "both legs in ONE process over identical bytes; naive ="
                    " CSVPLUS_JOIN_SKEW=0 (hash-repartition only), skew ="
                    " detection + broadcast tier for heavy keys + shrunken"
                    " exchange capacity for the tail; parity is FULL-result"
                    " positional per-column checksums, not a prefix"
                ),
            }
        )
    )


def _multiway_main(n_orders: int) -> None:
    """The ``--multiway`` tier (ISSUE 17): the cascaded 3-way join vs
    the single-pass multiway operator over the SAME Zipf-skewed bytes,
    both legs in ONE process.

    Same measurement discipline as the skew tier — cold pass, warm
    best-of-3 with telemetry off and zero recompiles asserted, then one
    instrumented pass for the per-stage table — with two additions:

    * both legs execute through :class:`PlanCache` (the production
      serving path), differing ONLY in ``CSVPLUS_MULTIWAY``: the
      cascaded leg admits with the fuse pass off (optimizer otherwise
      on, skew tier on), the multiway leg must actually FUSE
      (``stats()["fused"] >= 1`` is asserted, not assumed);
    * each leg runs under its own fresh :class:`MemoryWatermark`
      sampler (VmHWM is process-lifetime and cannot be reset), with a
      gc + host-staging trim between legs, so the artifact carries a
      per-leg RSS peak — the number the tentpole's
      "kill the intermediate" claim is judged on.

    Parity is FULL-result positional per-column checksums between the
    legs (hard assert); the RSS-below and throughput-at-least targets
    are recorded as booleans plus a per-stage ``obs diff`` attribution
    table (which stages the fusion removed or shrank).
    """
    # same knobs as the skew tier: partition tier must engage on the
    # 1.5M-key customer index, detection sized for the s=1.1 tail
    os.environ.setdefault("CSVPLUS_PARTITION_MIN_KEYS", "1000000")
    os.environ.setdefault("CSVPLUS_JOIN_SKEW_SAMPLE", "16384")
    os.environ.setdefault("CSVPLUS_JOIN_SKEW_THRESHOLD", "0.002")
    os.environ["CSVPLUS_JOIN_SKEW"] = "1"  # BOTH legs skew-aware
    n_cust = int(os.environ.get("CSVPLUS_BENCH_MESH_ZIPF_CUSTOMERS", 1_500_000))
    zipf_s = float(os.environ.get("CSVPLUS_BENCH_MESH_ZIPF_S", 1.1))

    import gc

    import bench  # repo root is on sys.path (header insert)

    opath, cpath = bench.zipf_fact_table(n_orders, n_cust, s=zipf_s)
    print(
        f"zipf orders file: {opath} ({os.path.getsize(opath) / 1e9:.2f} GB),"
        f" s={zipf_s}, {n_cust:,} customers",
        file=sys.stderr,
    )

    import jax

    from csvplus_tpu import FromFile
    from csvplus_tpu.columnar.ingest import _trim_host_staging
    from csvplus_tpu.native.scanner import _ingest_workers
    from csvplus_tpu.obs.diff import diff_stage_tables
    from csvplus_tpu.obs.joinskew import joinskew
    from csvplus_tpu.obs.memory import MemoryWatermark, host_header
    from csvplus_tpu.obs.recompile import RecompileWatch
    from csvplus_tpu.serve.plancache import PlanCache
    from csvplus_tpu.utils.checksum import checksum_device_table
    from csvplus_tpu.utils.observe import telemetry

    assert len(jax.devices()) >= N_SHARDS, jax.devices()

    t0 = time.perf_counter()
    orders = FromFile(opath).OnDevice(shards=N_SHARDS)
    orders.plan.table.sync()
    t_ingest = time.perf_counter() - t0
    table = orders.plan.table
    assert getattr(table, "_pre_sharded", False), "sharded ingest did not engage"
    print(
        f"ingest (sharded): {n_orders / t_ingest:,.0f} rows/s"
        f" ({t_ingest:,.1f}s), rss {_rss_mb():,.0f} MB",
        file=sys.stderr,
    )

    from northstar import DATA_DIR  # products.csv lives in the same cache

    t0 = time.perf_counter()
    cust_idx = FromFile(cpath).OnDevice().UniqueIndexOn("id")
    prod_idx = (
        FromFile(os.path.join(DATA_DIR, "products.csv"))
        .OnDevice()
        .UniqueIndexOn("prod_id")
    )
    t_index = time.perf_counter() - t0
    print(f"index build: {t_index:,.1f}s", file=sys.stderr)

    # the SAME submitted plan for both legs: Scan -> Join(cust) ->
    # Join(prod); only the admission-time CSVPLUS_MULTIWAY flag differs
    plan = orders.Join(cust_idx, "cust_id").Join(prod_idx).plan
    joinskew.reset()

    legs = {}
    stage_tables = {}
    checksums = {}
    recipes = {}
    for mode, flag in (("cascaded", "0"), ("multiway", "1")):
        os.environ["CSVPLUS_MULTIWAY"] = flag
        cache = PlanCache()
        # level the memory baseline before each leg's sampler starts:
        # drop the previous leg's released buffers and return freed
        # host staging to the OS, so each watermark measures its own
        # leg's working set, not the other's allocator retention
        gc.collect()
        _trim_host_staging()
        wm = MemoryWatermark(interval_s=0.02).start()
        t0 = time.perf_counter()
        result = cache.execute(plan)  # cold: verify+optimize+compile
        t_cold = time.perf_counter() - t0
        assert result.nrows == n_orders, result.nrows
        cols = sorted(result.columns)
        checksums[mode] = checksum_device_table(result, cols, positional=True)
        result = None  # release before the warm passes (see main())
        warm_times = []
        with RecompileWatch() as recompiles:
            for _ in range(3):
                t0 = time.perf_counter()
                r = cache.execute(plan)
                warm_times.append(time.perf_counter() - t0)
                r = None
        recompiles.assert_zero(f"mesh warm multiway-tier joins ({mode})")
        t_warm = min(warm_times)
        with telemetry.collect() as jrecords:
            cache.execute(plan)
            join_records = list(jrecords)
        telemetry.records[:] = join_records
        stage_tables[mode] = telemetry.to_json()["stage_table"]
        telemetry.reset()
        wm.stop()
        stats = cache.stats()
        if mode == "multiway":
            assert stats["fused"] >= 1, f"multiway leg did not fuse: {stats}"
        else:
            assert stats["fused"] == 0, f"cascaded leg fused: {stats}"
        recipe = cache.executable_for(plan).recipe  # warm hit
        recipes[mode] = {
            "steps": [
                [s[0]]
                + [list(a) if isinstance(a, (list, tuple)) else a for a in s[1:]]
                for s in (recipe.steps if recipe is not None else ())
            ],
            "join_order": list(getattr(recipe, "join_order", ()) or ()),
        }
        legs[mode] = {
            "cold_sec": round(t_cold, 2),
            "warm_sec": round(t_warm, 2),
            "warm_passes_sec": [round(t, 2) for t in warm_times],
            "rows_per_sec_warm": round(n_orders / t_warm, 1),
            "recompiles_warm": recompiles.delta(),
            "peak_host_rss_mb": round(wm.rss_peak_mb, 1),
            "rss_start_mb": wm.attrs()["rss_start_mb"],
            "plancache_fused": stats["fused"],
        }
        print(
            f"3-way join [{mode}]: warm best-of-3"
            f" {n_orders / t_warm:,.0f} rows/s ({t_warm:,.2f}s; passes"
            f" {', '.join(f'{t:,.2f}s' for t in warm_times)});"
            f" leg rss peak {wm.rss_peak_mb:,.0f} MB"
            f" (start {legs[mode]['rss_start_mb']:,.0f} MB)",
            file=sys.stderr,
        )

    assert checksums["multiway"] == checksums["cascaded"], (
        "bitwise parity broke: multiway checksums differ from the"
        " CSVPLUS_MULTIWAY=0 cascade over the same bytes"
    )
    snap = joinskew.counters_snapshot()
    # multiway engagement counters are labelled by the fused dims' key
    # columns joined with '+'; routing counters by the customer index's
    # key column ("id")
    mw_counters = snap.get("id+prod_id")
    assert mw_counters and mw_counters.get("multiway_joins", 0) >= 5, (
        f"multiway counters never landed: {snap}"
    )
    skew_counters = snap.get("id")

    rss_below = (
        legs["multiway"]["peak_host_rss_mb"] < legs["cascaded"]["peak_host_rss_mb"]
    )
    thr_at_least = (
        legs["multiway"]["rows_per_sec_warm"] >= legs["cascaded"]["rows_per_sec_warm"]
    )
    speedup = legs["cascaded"]["warm_sec"] / legs["multiway"]["warm_sec"]
    # per-stage obs-diff attribution: which stages the fusion removed
    # (the interior probe/gather/merge) and which it grew (expand)
    stage_diff = diff_stage_tables(
        stage_tables["cascaded"], stage_tables["multiway"]
    )
    for check, ok in (("rss below cascaded", rss_below),
                      ("throughput >= cascaded", thr_at_least)):
        if not ok:
            print(f"WARNING: multiway target missed: {check}", file=sys.stderr)
    print(
        f"parity: full positional checksums identical across operators;"
        f" multiway {speedup:,.2f}x vs cascaded, rss"
        f" {legs['multiway']['peak_host_rss_mb']:,.0f} vs"
        f" {legs['cascaded']['peak_host_rss_mb']:,.0f} MB;"
        f" counters: {mw_counters}",
        file=sys.stderr,
    )

    print(
        json.dumps(
            {
                "metric": "northstar_mesh_threeway_join_multiway",
                "rows": n_orders,
                "n_shards": N_SHARDS,
                "n_customers": n_cust,
                "zipf_s": zipf_s,
                "ingest_workers": _ingest_workers(),
                "backend": jax.default_backend(),
                **host_header(),
                "env_overrides": {
                    k: os.environ[k]
                    for k in (
                        "CSVPLUS_PARTITION_MIN_KEYS",
                        "CSVPLUS_JOIN_SKEW_SAMPLE",
                        "CSVPLUS_JOIN_SKEW_THRESHOLD",
                        "CSVPLUS_JOIN_SKEW",
                        "CSVPLUS_STREAM_MIN_BYTES",
                    )
                },
                "ingest_rows_per_sec": round(n_orders / t_ingest, 1),
                "join_rows_per_sec_warm_multiway": legs["multiway"][
                    "rows_per_sec_warm"
                ],
                "join_rows_per_sec_warm_cascaded": legs["cascaded"][
                    "rows_per_sec_warm"
                ],
                "multiway_speedup": round(speedup, 2),
                "rss_below_cascaded": rss_below,
                "throughput_ge_cascaded": thr_at_least,
                "legs": legs,
                "recipes": recipes,
                "multiway_counters": mw_counters,
                "skew_counters": skew_counters,
                "parity_bitwise": True,
                "full_result_checksums": checksums["multiway"],
                "peak_host_rss_mb": round(_rss_mb(), 1),
                "stage_table_cascaded": stage_tables["cascaded"],
                "stage_table_multiway": stage_tables["multiway"],
                "stage_diff_cascaded_vs_multiway": stage_diff,
                "note": (
                    "both legs in ONE process over identical bytes, both"
                    " through PlanCache with the skew tier on; cascaded ="
                    " CSVPLUS_MULTIWAY=0 (Join->Join with a materialized"
                    " intermediate), multiway = the rewriter's cost-chosen"
                    " fused single-pass operator; per-leg RSS peaks are"
                    " fresh-sampler watermarks (VmHWM is process-lifetime),"
                    " cascaded leg runs first; parity is FULL-result"
                    " positional per-column checksums"
                ),
            }
        )
    )


if __name__ == "__main__":
    main()
