"""North-star scale run: the 100M-row 3-way join, end-to-end from CSV.

BASELINE.md's target configuration (orders ⋈ customers ⋈ products,
reference pipeline csvplus.go:539-583 / README.md:54-65) at 100M orders
rows, driven through the PUBLIC API: `FromFile(...).OnDevice()` — which
engages the chunk-streamed ingest tier for the ~2.6GB file — then two
`UniqueIndexOn` build sides and two chained `Join`s executed by the
columnar device planner.

Usage: python examples/northstar.py [n_orders]   (default 100_000_000)

Prints per-phase rates, peak host RSS (the streamed-ingest memory bound),
and a final JSON line for the record.
"""

from __future__ import annotations

import json
import os
import resource
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DATA_DIR = os.environ.get("NORTHSTAR_DIR", "/tmp/northstar_data")
N_CUST = 100_000
N_PROD = 1_000


def _rss_mb() -> float:
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0


def generate(n_orders: int) -> str:
    """Write orders/customers/products CSVs (cached across runs)."""
    os.makedirs(DATA_DIR, exist_ok=True)
    opath = os.path.join(DATA_DIR, f"orders_{n_orders}_v2.csv")  # v2: +order_id
    old = os.path.join(DATA_DIR, f"orders_{n_orders}.csv")
    if os.path.exists(old):
        os.remove(old)  # pre-v2 cache: don't leak GBs in the data dir
    cpath = os.path.join(DATA_DIR, "customers.csv")
    ppath = os.path.join(DATA_DIR, "products.csv")
    if not os.path.exists(cpath):
        with open(cpath, "w") as f:
            f.write("id,name\n")
            for i in range(N_CUST):
                f.write(f"c{i},name{i % 9973}\n")
    if not os.path.exists(ppath):
        with open(ppath, "w") as f:
            f.write("prod_id,product,price\n")
            for i in range(N_PROD):
                f.write(f"p{i},prod{i},{(i % 9900) / 100 + 0.99:.2f}\n")
    if not os.path.exists(opath):
        rng = np.random.default_rng(20160914)
        t0 = time.perf_counter()
        with open(opath, "w") as f:
            # order_id is UNIQUE across all 100M rows: the column that
            # exercises the device-lane dictionary RSS bound at its
            # design scale (VERDICT r3 next #5)
            f.write("order_id,cust_id,prod_id,qty\n")
            chunk = 2_000_000
            for base in range(0, n_orders, chunk):
                n = min(chunk, n_orders - base)
                oid = np.arange(base, base + n)
                cust = rng.integers(0, N_CUST, n)
                prod = rng.integers(0, N_PROD, n)
                qty = rng.integers(1, 101, n)
                lines = np.char.add(
                    np.char.add(
                        np.char.add("o", oid.astype(np.str_)),
                        np.char.add(",c", cust.astype(np.str_)),
                    ),
                    np.char.add(
                        np.char.add(",p", prod.astype(np.str_)),
                        np.char.add(",", qty.astype(np.str_)),
                    ),
                )
                f.write("\n".join(lines.tolist()))
                f.write("\n")
                print(
                    f"  gen {base + n:,}/{n_orders:,} rows"
                    f" ({time.perf_counter() - t0:,.0f}s)",
                    file=sys.stderr,
                )
    return opath


def main() -> None:
    n_orders = int(sys.argv[1]) if len(sys.argv) > 1 else 100_000_000
    opath = generate(n_orders)
    print(
        f"orders file: {opath} ({os.path.getsize(opath) / 1e9:.2f} GB), "
        f"rss after gen {_rss_mb():,.0f} MB",
        file=sys.stderr,
    )

    import jax

    from csvplus_tpu import FromFile, Take

    backend = jax.default_backend()
    t0 = time.perf_counter()
    orders = FromFile(opath).OnDevice()
    # sync ingest (async dispatch would stop the clock early); one
    # scalar round trip forces every uploaded column
    orders.plan.table.sync()
    t_ingest = time.perf_counter() - t0
    rss_ingest = _rss_mb()
    lane_cols = [
        name
        for name, col in orders.plan.table.columns.items()
        if getattr(col, "dev_dictionary", None) is not None
        and col._dictionary is None
    ]
    print(
        f"ingest: {n_orders / t_ingest:,.0f} rows/s ({t_ingest:,.1f}s), "
        f"peak rss {rss_ingest:,.0f} MB, device-lane columns: {lane_cols}",
        file=sys.stderr,
    )

    t0 = time.perf_counter()
    cust_idx = (
        FromFile(os.path.join(DATA_DIR, "customers.csv"))
        .OnDevice()
        .UniqueIndexOn("id")
    )
    prod_idx = (
        FromFile(os.path.join(DATA_DIR, "products.csv"))
        .OnDevice()
        .UniqueIndexOn("prod_id")
    )
    t_index = time.perf_counter() - t0
    print(f"index build (device, 101K rows): {t_index:,.1f}s", file=sys.stderr)

    # the join itself through the public API: columnar planner, device
    # probe + gathers, materialized as a device-resident table
    joined = orders.Join(cust_idx, "cust_id").Join(prod_idx)
    t0 = time.perf_counter()
    table = joined.to_device_table().sync()
    t_join = time.perf_counter() - t0
    assert table.nrows == n_orders, table.nrows
    print(
        f"3-way join: {n_orders / t_join:,.0f} rows/s ({t_join:,.2f}s), "
        f"{table.nrows:,} result rows (cold, includes compiles)",
        file=sys.stderr,
    )

    # warm re-run: the steady-state rate once executables are cached
    t0 = time.perf_counter()
    joined.to_device_table().sync()
    t_warm = time.perf_counter() - t0
    print(
        f"3-way join (warm): {n_orders / t_warm:,.0f} rows/s ({t_warm:,.2f}s)",
        file=sys.stderr,
    )

    # FULL-RESULT verification (BASELINE: "identical output rows"):
    # 1. exact result row count (asserted above: table.nrows == n_orders)
    # 2. the HOST EXECUTOR runs the same pipeline on a deterministic
    #    >=1M-row prefix slice and its POSITIONAL per-column row-hash
    #    sums must equal the device result's over the same slice — the
    #    position-weighted sums are order-sensitive, so a permutation
    #    or cross-row cell swap inside the prefix fails the check with
    #    ordinary 32-bit-checksum confidence (ADVICE r3), on top of
    #    every cell value being covered
    # 3. positional checksums over ALL result rows, computed on device
    #    (one gather + reduce per column) and recorded in the JSON so
    #    independent runs/backends can be compared bit-for-bit
    from csvplus_tpu import StopPipeline, take_rows
    from csvplus_tpu.utils.checksum import (
        checksum_device_table,
        checksum_host_rows,
    )

    sample = min(1_000_000, n_orders)
    head: list = []

    def collect(row):
        head.append(row)
        if len(head) >= sample:
            raise StopPipeline

    Take(FromFile(opath))(collect)
    h_cust = Take(FromFile(os.path.join(DATA_DIR, "customers.csv"))).UniqueIndexOn(
        "id"
    )
    h_prod = Take(FromFile(os.path.join(DATA_DIR, "products.csv"))).UniqueIndexOn(
        "prod_id"
    )
    t0 = time.perf_counter()
    host_rows = take_rows(head).Join(h_cust, "cust_id").Join(h_prod).to_rows()
    cols = sorted(table.columns)
    want_sums = checksum_host_rows(host_rows, cols, positional=True)
    got_sums = checksum_device_table(table, cols, limit=sample, positional=True)
    assert got_sums == want_sums, (
        f"checksum mismatch on the first {sample} rows: "
        f"{got_sums} != {want_sums}"
    )
    # exact-row spot check on top of the checksums (first/last of slice)
    spots = np.array([0, sample - 1])
    assert table.to_rows(spots) == [host_rows[0], host_rows[-1]]
    t_verify = time.perf_counter() - t0
    print(
        f"parity: per-column checksums over the first {sample:,} rows match "
        f"the host executor exactly ({t_verify:,.1f}s)",
        file=sys.stderr,
    )
    full_sums = checksum_device_table(table, cols, positional=True)
    print(f"full-result column checksums ({table.nrows:,} rows): {full_sums}",
          file=sys.stderr)

    print(
        json.dumps(
            {
                "metric": "northstar_threeway_join",
                "rows": n_orders,
                "backend": backend,
                "ingest_rows_per_sec": round(n_orders / t_ingest, 1),
                "join_rows_per_sec": round(n_orders / t_join, 1),
                "join_rows_per_sec_warm": round(n_orders / t_warm, 1),
                "end_to_end_sec": round(t_ingest + t_index + t_join, 1),
                "peak_host_rss_mb": round(_rss_mb(), 1),
                "ingest_rss_mb": round(rss_ingest, 1),
                "device_lane_columns": lane_cols,
                "parity_checked_rows": sample,
                "full_result_checksums": full_sums,
                **(
                    {
                        "note": "backend=cpu: jax device arrays (codes + "
                        "lane dictionaries + join result) live in host RAM, "
                        "so peak_host_rss_mb includes what would be HBM on "
                        "a TPU backend; the host-side streamed-ingest bound "
                        "is evidenced by device_lane_columns"
                    }
                    if backend == "cpu"
                    else {}
                ),
            }
        )
    )


if __name__ == "__main__":
    main()
