"""Quickstart: the reference README's two examples on this framework.

Run:  python examples/quickstart.py [--device]
(--device runs the pipelines through the columnar device executor.)
"""

import csv
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
import csvplus_tpu as csvplus


def make_corpus(root):
    with open(f"{root}/people.csv", "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["id", "name", "surname"])
        for i, (n, s) in enumerate(
            [("Amelia", "Smith"), ("Amelia", "Jones"), ("Jack", "Taylor")]
        ):
            w.writerow([str(i), n, s])
    with open(f"{root}/stock.csv", "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["prod_id", "product", "price"])
        w.writerow(["0", "orange", "0.03"])
        w.writerow(["1", "apple", "0.02"])
    with open(f"{root}/orders.csv", "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["cust_id", "prod_id", "qty", "ts"])
        w.writerow(["1", "0", "38", "2016-09-14T08:48:22+01:00"])
        w.writerow(["2", "1", "5", "2016-09-14T09:00:00+01:00"])


def main():
    on_device = "--device" in sys.argv
    with tempfile.TemporaryDirectory() as root:
        make_corpus(root)

        def src(path, *cols):
            r = csvplus.FromFile(path).SelectColumns(*cols)
            return r.OnDevice() if on_device else csvplus.Take(r)

        # example 1: filter + map + csv out (README.md:20-26 analogue)
        out = f"{root}/out.csv"
        src(f"{root}/people.csv", "name", "surname", "id") \
            .Filter(csvplus.Like({"name": "Amelia"})) \
            .Map(csvplus.SetValue("name", "Julia")) \
            .ToCsvFile(out, "name", "surname")
        print(open(out).read())

        # example 2: 3-table join (README.md:34-65 analogue)
        cust = src(f"{root}/people.csv", "id", "name", "surname").UniqueIndexOn("id")
        prod = src(f"{root}/stock.csv", "prod_id", "product", "price").UniqueIndexOn("prod_id")
        if on_device:
            cust.OnDevice()
            prod.OnDevice()
        orders = src(f"{root}/orders.csv", "cust_id", "prod_id", "qty", "ts")
        for row in orders.Join(cust, "cust_id").Join(prod):
            print(
                f'{row["name"]} {row["surname"]} bought {row["qty"]} '
                f'{row["product"]}s for £{row["price"]} each on {row["ts"]}'
            )


if __name__ == "__main__":
    try:
        main()
    except BrokenPipeError:  # e.g. piping into `head`
        import os, sys

        try:
            sys.stdout.close()
        except Exception:
            pass
        os._exit(0)
