"""Distributed demo: an 8-shard mesh pipeline with a partitioned join.

Run (CPU mesh):
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/sharded_join.py

On a real multi-chip TPU slice the same code runs over ICI: the stream
table is GSPMD row-sharded, small indexes broadcast, and build sides over
DeviceIndex.PARTITION_MIN_KEYS probe through the shard_map all_to_all
shuffle (csvplus_tpu/parallel/pjoin.py).
"""

import csv
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
import csvplus_tpu as csvplus
from csvplus_tpu import Like, telemetry


def main():
    import jax

    n_dev = len(jax.devices())
    print(f"devices: {n_dev} x {jax.devices()[0].platform}")

    with tempfile.TemporaryDirectory() as root:
        orders = f"{root}/orders.csv"
        with open(orders, "w", newline="") as f:
            w = csv.writer(f)
            w.writerow(["order_id", "cust_id", "qty"])
            for i in range(100_000):
                w.writerow([str(i), f"c{i % 5000}", str(i % 90 + 1)])
        people = f"{root}/people.csv"
        with open(people, "w", newline="") as f:
            w = csv.writer(f)
            w.writerow(["id", "name"])
            for i in range(5000):
                w.writerow([f"c{i}", f"name{i % 97}"])

        cust = csvplus.Take(
            csvplus.FromFile(people).SelectColumns("id", "name")
        ).UniqueIndexOn("id").OnDevice()

        with telemetry.collect() as stages:
            top = (
                csvplus.FromFile(orders)
                .OnDevice(shards=n_dev)  # row-sharded over the whole mesh
                .SelectColumns("cust_id", "qty")
                .Join(cust, "cust_id")
                .Filter(Like({"name": "name42"}))
                .Top(5)
                .ToRows()
            )
        for row in top:
            print(dict(row))
        print()
        print(telemetry.report())


if __name__ == "__main__":
    try:
        main()
    except BrokenPipeError:
        os._exit(0)
