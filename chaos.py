#!/usr/bin/env python
"""`make chaos`: seeded fault-injection differential gate (ISSUE 8, r09).

Runs seeded fault schedules against the four workload shapes —
serve load, K-worker streamed ingest, the 8-way mesh join, and the
mutable-index compactor — and holds the recovery ladder to the
differential contract:

* when recovery is possible (transient device faults within the retry
  budget, breaker fallback, crashed ingest workers) the results must be
  BITWISE-EQUAL to the fault-free oracle, with zero warm recompiles on
  the retry path (``RecompileWatch.assert_zero``);
* when it is not (fatal faults, dispatcher death, I/O errors) the
  failure must surface as its TYPED error — ``ServerCrashed`` for every
  pending future within 1s of a dispatcher crash, row-numbered
  ``DataSourceError`` for source I/O — never a hang or a silent wrong
  answer.  Every case runs under a watchdog timeout, so a hang IS a
  failure, not a stuck CI job.
* the disarmed injection hooks must cost <= 1% of a served request
  (measured here, recorded in the artifact — the same discipline as
  `make trace-smoke`'s disabled-hook gate).

The ISSUE 12 window extends the matrix to the materialized-view tier:
a crash at ``views:refresh`` inside a serving write cycle must leave
the PRIOR epoch-pinned snapshot live (same epoch, same checksums),
every unapplied tier event queued, and the dispatcher alive — and the
disarmed retry must converge the view back to bitwise parity with a
from-scratch execution of its registered plan.

The ISSUE 13 extension asserts the crash FLIGHT RECORDER on the two
terminal windows: a dispatcher crash and a ``views:refresh`` crash
must each leave an atomically-written flight dump that parses and
names the firing fault site in its event timeline.

Contract (matches the benches): diagnostics go to stderr, stdout
carries ONE compact JSON line; CHAOS_r13.json records the full
evidence — per-case injection counts (``FaultPlan.snapshot``), recovery
outcomes, serve retry/degrade metrics, telemetry counters
(``ingest.worker_recovered``), flight-dump evidence, and the overhead
measurement.  Exits nonzero when any case fails its contract.
"""

from __future__ import annotations

import contextlib
import json
import os
import subprocess
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

# hermetic 8-device CPU mesh, same recipe as tests/conftest.py
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["CSVPLUS_TPU_HERMETIC"] = "1"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

#: Watchdog bound per chaos case: a case that cannot finish inside this
#: is a hang, which is exactly what the resilience layer must prevent.
CASE_TIMEOUT_S = float(os.environ.get("CSVPLUS_CHAOS_CASE_TIMEOUT", 120))
ARTIFACT = os.path.join(REPO, "CHAOS_r13.json")
#: Disarmed-hook budget: injection sites on the serve path may cost at
#: most this fraction of one served request.
OVERHEAD_BUDGET_PCT = 1.0


def _with_timeout(name: str, fn):
    """Run one chaos case under the watchdog.  Returns the case record;
    a timeout or an escape is a recorded failure, never a hang of the
    gate itself."""
    box: dict = {}

    def run():
        try:
            box["result"] = fn()
        except BaseException as e:  # recorded + failed, gate must finish
            box["error"] = f"{type(e).__name__}: {e}"

    t0 = time.perf_counter()
    th = threading.Thread(target=run, name=f"chaos-{name}", daemon=True)
    th.start()
    th.join(CASE_TIMEOUT_S)
    elapsed = time.perf_counter() - t0
    if th.is_alive():
        rec = {"ok": False, "error": f"timeout after {CASE_TIMEOUT_S}s (hang)"}
    elif "error" in box:
        rec = {"ok": False, "error": box["error"]}
    else:
        rec = dict(box["result"])
        rec.setdefault("ok", True)
    rec["seconds"] = round(elapsed, 3)
    status = "ok" if rec["ok"] else f"FAIL ({rec.get('error', 'contract')})"
    sys.stderr.write(f"chaos[{name}]: {status} in {elapsed:.2f}s\n")
    return rec


def _build_index(n=20_000):
    import numpy as np

    import csvplus_tpu as cp
    from csvplus_tpu.columnar.table import DeviceTable

    ids = np.arange(n, dtype=np.int64) * 7 % (n * 3)
    t = DeviceTable.from_pylists(
        {
            "id": np.char.add("c", ids.astype(np.str_)).tolist(),
            "v": np.arange(n).astype(np.str_).tolist(),
        },
        device="cpu",
    )
    return cp.take(t).index_on("id").sync(), ids


def _probes(ids, n, seed=0):
    import numpy as np

    rng = np.random.default_rng(seed)
    ps = [f"c{int(v)}" for v in rng.choice(ids, n)]
    ps[::17] = ["nope"] * len(ps[::17])
    return ps


# ---- serve load under faults ---------------------------------------------


def case_serve_retry(idx, ids):
    """Transient device faults on the coalesced lookup: absorbed by
    retries, bitwise-equal results, zero warm recompiles."""
    from csvplus_tpu.obs.recompile import RecompileWatch
    from csvplus_tpu.resilience import faults
    from csvplus_tpu.resilience.faults import FaultPlan
    from csvplus_tpu.resilience.retry import RetryPolicy
    from csvplus_tpu.serve import LookupServer

    probes = _probes(ids, 600, seed=1)
    serial = [idx.find(p).to_rows() for p in probes]
    with LookupServer(idx) as srv:
        srv.retry_policy = RetryPolicy(max_attempts=3, base_s=1e-4, cap_s=1e-3)
        for f in [srv.submit(p) for p in probes[:50]]:  # warm off-watch
            f.result(timeout=30.0)
        with RecompileWatch() as w:
            with faults.active(
                FaultPlan(
                    [{"site": "serve:bounds", "at": [0, 2, 5], "error": "device"}],
                    seed=9,
                )
            ) as plan:
                futs = [srv.submit(p) for p in probes]
                got = [f.result(timeout=30.0) for f in futs]
        w.assert_zero("chaos serve retries")
        snap = srv.snapshot()
    return {
        "ok": got == serial and snap["retried"] >= 1 and snap["failed"] == 0,
        "bitwise_equal": got == serial,
        "recompile_observable": w.observable(),
        "injections": plan.snapshot(),
        "metrics": {k: snap[k] for k in ("retried", "degraded", "failed")},
    }


def case_serve_degrade(idx, ids):
    """Retries exhaust under a 100% device-fault schedule: the breaker
    trips onto the host oracle (bitwise parity), then half-open probing
    recovers the device path once faults stop."""
    from csvplus_tpu.resilience import faults
    from csvplus_tpu.resilience.degrade import CircuitBreaker
    from csvplus_tpu.resilience.faults import FaultPlan
    from csvplus_tpu.resilience.retry import RetryPolicy
    from csvplus_tpu.serve import LookupServer

    probes = _probes(ids, 300, seed=2)
    serial = [idx.find(p).to_rows() for p in probes]
    with LookupServer(idx) as srv:
        srv.retry_policy = RetryPolicy(max_attempts=2, base_s=1e-4, cap_s=1e-3)
        srv.breaker = CircuitBreaker(threshold=2, cooldown_s=0.05)
        with faults.active(
            FaultPlan([{"site": "serve:bounds", "every": 1, "error": "device"}])
        ) as plan:
            futs = [srv.submit(p) for p in probes]
            got = [f.result(timeout=30.0) for f in futs]
        snap = srv.snapshot()
        opened = srv.breaker.state == "open"
        time.sleep(0.06)  # cooldown: next route is the half-open probe
        again = [srv.submit(p) for p in probes[:20]]
        recovered = [f.result(timeout=30.0) for f in again] == serial[:20]
        closed = srv.breaker.state == "closed"
    return {
        "ok": got == serial
        and snap["failed"] == 0
        and snap["degraded"] >= len(probes)
        and opened
        and recovered
        and closed,
        "bitwise_equal_degraded": got == serial,
        "breaker_opened": opened,
        "breaker_recovered": closed,
        "injections": plan.snapshot(),
        "metrics": {k: snap[k] for k in ("retried", "degraded", "failed")},
    }


@contextlib.contextmanager
def _flight_dir():
    """Point the crash flight recorder at a fresh scratch dir for one
    case, restoring the prior CSVPLUS_FLIGHT_DIR on exit."""
    d = tempfile.mkdtemp(prefix="chaos_flight_")
    prev = os.environ.get("CSVPLUS_FLIGHT_DIR")
    os.environ["CSVPLUS_FLIGHT_DIR"] = d
    try:
        yield d
    finally:
        if prev is None:
            os.environ.pop("CSVPLUS_FLIGHT_DIR", None)
        else:
            os.environ["CSVPLUS_FLIGHT_DIR"] = prev


def _flight_evidence(flight_dir, site, timeout_s=10.0):
    """Parse every flight dump a crash window left in *flight_dir* and
    report whether one names *site* as a fired fault in its timeline —
    the ISSUE 13 post-mortem contract.  Waits out the crash thread's
    in-flight write: futures unblock before the dump finishes."""
    deadline = time.perf_counter() + timeout_s
    names: list = []
    while not names and time.perf_counter() < deadline:
        names = sorted(
            f for f in os.listdir(flight_dir)
            if f.startswith("csvplus_flight.") and f.endswith(".json")
        )
        if not names:
            time.sleep(0.01)
    parsed = 0
    named = False
    reasons = []
    for name in names:
        try:
            with open(os.path.join(flight_dir, name)) as f:
                payload = json.load(f)
        except (OSError, ValueError) as err:
            reasons.append(f"unparseable: {type(err).__name__}")
            continue
        parsed += 1
        reasons.append(payload.get("reason"))
        for ev in payload.get("events", ()):
            if ev.get("kind") == "fault:fired" and ev.get("site") == site:
                named = True
    return {
        "ok": bool(names) and parsed == len(names) and named,
        "dumps": len(names),
        "parsed": parsed,
        "reasons": reasons,
        "names_fault_site": named,
    }


def case_dispatcher_crash(idx, ids):
    """A fatal fault in the dispatcher: every pending future fails with
    typed ServerCrashed in under a second; post-mortem submits fail
    fast at admission; the flight recorder leaves a parseable dump that
    names the firing fault site."""
    from csvplus_tpu.resilience import faults
    from csvplus_tpu.resilience.faults import FaultPlan
    from csvplus_tpu.resilience.retry import ServerCrashed
    from csvplus_tpu.serve import LookupServer

    with _flight_dir() as flight_dir:
        srv = LookupServer(idx, tick_us=20_000)  # hold the doomed batch open
        srv.start()
        try:
            with faults.active(
                FaultPlan(
                    [{"site": "serve:dispatch", "at": [0], "error": "fatal"}]
                )
            ) as plan:
                futs = []
                for v in ids[:16]:
                    try:
                        futs.append(srv.submit(f"c{int(v)}"))
                    except ServerCrashed:
                        break
                t0 = time.perf_counter()
                typed = 0
                for f in futs:
                    try:
                        f.result(timeout=1.0)
                    except ServerCrashed:
                        typed += 1
                    except BaseException:
                        pass
                unblock_s = time.perf_counter() - t0
            try:
                srv.submit(f"c{int(ids[0])}")
                post_typed = False
            except ServerCrashed:
                post_typed = True
            flight = _flight_evidence(flight_dir, "serve:dispatch")
            return {
                "ok": bool(futs)
                and typed == len(futs)
                and unblock_s < 1.0
                and post_typed
                and flight["ok"],
                "pending_futures": len(futs),
                "typed_failures": typed,
                "unblock_seconds": round(unblock_s, 4),
                "post_crash_submit_typed": post_typed,
                "flight": flight,
                "injections": plan.snapshot(),
            }
        finally:
            srv.stop()


# ---- K-worker streamed ingest under faults -------------------------------


def _chaos_csv(root, rows=2000):
    path = os.path.join(root, "chaos_ingest.csv")
    with open(path, "w") as f:
        f.write("k,v\n")
        for i in range(rows):
            f.write(f"k{i},v{i * 3}\n")
    return path


def _stream_fold(path, workers, chunk_bytes=512):
    import numpy as np

    from csvplus_tpu import DataSourceError, from_file
    from csvplus_tpu.native import scanner as native

    out = []
    try:
        for names, encoded, n in native.stream_encoded_chunks(
            from_file(path), path, chunk_bytes=chunk_bytes, workers=workers
        ):
            chunk = {}
            for c, enc in encoded.items():
                if len(enc) == 3 and enc[0] == "int":
                    chunk[c] = ("typed", enc[1], enc[2].tolist())
                else:
                    chunk[c] = (
                        "dict",
                        [bytes(x) for x in enc[0].tolist()],
                        np.asarray(enc[1]).tolist(),
                    )
            out.append((tuple(names), chunk, n))
    except DataSourceError as e:
        return ("exc", type(e).__name__, str(e), out)
    return ("ok", out)


def case_ingest_crash_recovery(tmp_root):
    """Crashed scan+encode workers re-execute their chunks: the emitted
    stream is bitwise-identical to the fault-free run for every K."""
    from csvplus_tpu.resilience import faults
    from csvplus_tpu.resilience.faults import FaultPlan

    path = _chaos_csv(tmp_root)
    oracle = _stream_fold(path, workers=1)
    per_k = {}
    ok = oracle[0] == "ok" and len(oracle[1]) > 4
    for k in (1, 2, 4):
        with faults.active(
            FaultPlan(
                [{"site": "ingest:worker", "at": [1, 3, 4, 9], "error": "crash"}],
                seed=5,
            )
        ) as plan:
            got = _stream_fold(path, workers=k)
        snap = plan.snapshot()
        per_k[str(k)] = {
            "bitwise_equal": got == oracle,
            "injections": snap,
        }
        ok = ok and got == oracle and snap["fired"].get("ingest:worker", 0) >= 1
    return {"ok": ok, "chunks": len(oracle[1]), "per_workers": per_k}


def case_ingest_read_fault_typed(tmp_root):
    """Unrecoverable read I/O faults surface as row-numbered
    DataSourceError with a K-independent outcome (emitted prefix +
    message), never a partial silent stream."""
    from csvplus_tpu.resilience import faults
    from csvplus_tpu.resilience.faults import FaultPlan

    path = _chaos_csv(tmp_root)
    outcomes = {}
    for k in (1, 2):
        with faults.active(
            FaultPlan([{"site": "ingest:read", "at": [2], "error": "io"}])
        ) as plan:
            outcomes[k] = _stream_fold(path, workers=k)
        snap = plan.snapshot()
    typed = outcomes[1][0] == "exc" and outcomes[1][1] == "DataSourceError"
    return {
        "ok": typed and outcomes[1] == outcomes[2],
        "typed": typed,
        "k_independent": outcomes[1] == outcomes[2],
        "error": outcomes[1][2] if typed else None,
        "injections": snap,
    }


# ---- mesh join under faults ----------------------------------------------


def case_mesh_join_under_ingest_faults(tmp_root):
    """The 8-way sharded mesh join with crashing ingest workers under
    its streamed build: recovered ingest keeps the join bitwise-equal
    to the fault-free run."""
    import csvplus_tpu.models.workloads as W
    from csvplus_tpu import Take, from_file
    from csvplus_tpu.resilience import faults
    from csvplus_tpu.resilience.faults import FaultPlan

    cust_path = os.path.join(tmp_root, "cust.csv")
    with open(cust_path, "w") as f:
        f.write("id,name\n")
        for i in range(120):
            f.write(f"u{i},name{i % 12}\n")
    orders_path = os.path.join(tmp_root, "orders.csv")
    with open(orders_path, "w") as f:
        f.write("oid,cust_id,amount\n")
        for i in range(4000):
            f.write(f"o{i},u{(i * 13) % 120},{i % 97}\n")

    def run_join():
        cust = Take(from_file(cust_path)).unique_index_on("id")
        cust.on_device("cpu")
        return W.sharded_join(from_file(orders_path), cust, shards=8).to_rows()

    # shrink the stream chunk so the ~60KB orders file really flows
    # through the staged multi-chunk ingest (default chunks are 64MB —
    # the whole file would be one establishment chunk with no worker
    # executions to crash)
    prev_env = {
        k: os.environ.get(k)
        for k in ("CSVPLUS_STREAM_CHUNK_BYTES", "CSVPLUS_STREAM_MIN_BYTES")
    }
    os.environ["CSVPLUS_STREAM_CHUNK_BYTES"] = "4096"
    os.environ["CSVPLUS_STREAM_MIN_BYTES"] = "1"  # tier gate: stream always
    try:
        oracle = run_join()
        with faults.active(
            FaultPlan(
                [{"site": "ingest:worker", "at": [1, 2], "error": "crash"}],
                seed=11,
            )
        ) as plan:
            got = run_join()
    finally:
        for k, v in prev_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    snap = plan.snapshot()
    return {
        "ok": got == oracle
        and len(oracle) == 4000
        and snap["fired"].get("ingest:worker", 0) >= 1,
        "bitwise_equal": got == oracle,
        "rows": len(oracle),
        "injections": snap,
    }


# ---- storage: compactor crash safety (ISSUE 9) ---------------------------


def case_storage_compact_crash():
    """A compactor crash — at entry or in the pre-swap window — must
    leave the pre-compaction tier set intact (same epoch, same deltas,
    same answers) and a retry must compact to full rebuild parity."""
    from csvplus_tpu.resilience import faults
    from csvplus_tpu.resilience.faults import FaultPlan, InjectedFatalError
    from csvplus_tpu.row import Row
    from csvplus_tpu.source import take_rows
    from csvplus_tpu.storage import (
        MutableIndex,
        index_checksums,
        rebuild_reference,
    )

    mi = MutableIndex.create(
        take_rows([Row({"k": f"k{i % 41:03d}", "v": f"v{i}"}) for i in range(800)]),
        ["k"],
        ingest_device="cpu",
    )
    mi.append_rows([{"k": f"n{j}", "v": "x"} for j in range(30)])
    mi.append_rows([{"k": f"m{j}", "v": "y"} for j in range(20)])
    probes = [(f"k{i:03d}",) for i in range(0, 41, 3)] + [("n5",), ("zz",)]
    before = [
        [dict(r) for r in b] for b in mi.find_rows_many(probes)
    ]
    epoch0, deltas0 = mi.epoch, mi.delta_count
    injections = {}
    intact = True
    for hit, label in ((0, "at_entry"), (1, "pre_swap")):
        with faults.active(
            FaultPlan(
                [{"site": "storage:compact", "at": [hit], "error": "fatal"}],
                seed=13,
            )
        ) as plan:
            try:
                mi.compact_once()
                crashed = False
            except InjectedFatalError:
                crashed = True
            injections[label] = plan.snapshot()
        after = [
            [dict(r) for r in b] for b in mi.find_rows_many(probes)
        ]
        intact = (
            intact
            and crashed
            and mi.epoch == epoch0
            and mi.delta_count == deltas0
            and after == before
        )
    # disarmed retry compacts clean, bitwise-equal to the rebuild
    stats = mi.compact_once()
    parity = index_checksums(mi.tiers().base) == index_checksums(
        rebuild_reference(mi)
    )
    answers = [
        [dict(r) for r in b] for b in mi.find_rows_many(probes)
    ] == before
    return {
        "ok": intact and stats is not None and parity and answers,
        "tier_set_intact_after_crashes": intact,
        "retry_compacted_deltas": None if stats is None else stats["deltas"],
        "rebuild_parity": parity,
        "injections": injections,
    }


def case_wal_crash_matrix(tmp_root):
    """The ISSUE 10 crash-restart matrix: a subprocess child plays a
    fixed append/delete/compact op list over a durable MutableIndex
    under ``CSVPLUS_WAL_SYNC=always`` and is killed (injected fatal) at
    every fsync boundary of the write path — mid WAL append, mid
    segment seal, post-merge/pre-manifest-rename, post-rename/pre-WAL-
    truncate — plus a torn-tail partial frame.  Each window must
    recover checksums bitwise-equal to a fresh in-memory replay of
    exactly the acked ops (no acked-then-lost record), with zero warm
    recompiles on the recovered index."""
    import importlib.util

    from csvplus_tpu.obs.recompile import RecompileWatch
    from csvplus_tpu.storage import MutableIndex, index_checksums

    child_path = os.path.join(REPO, "tests", "wal_crash_child.py")
    spec = importlib.util.spec_from_file_location(
        "wal_crash_child", child_path
    )
    child = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(child)

    windows = {}
    for name, (fault, n_acked, n_replay) in sorted(
        child.CRASH_WINDOWS.items()
    ):
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        env["CSVPLUS_WAL_SYNC"] = "always"
        env.pop("CSVPLUS_FAULTS", None)
        env.pop("CSVPLUS_WAL_CHILD_TEAR", None)
        if fault is not None:
            env["CSVPLUS_FAULTS"] = json.dumps({"faults": [fault]})
        if name == "torn_tail":
            env["CSVPLUS_WAL_CHILD_TEAR"] = "1"
        workdir = os.path.join(tmp_root, f"wal-{name}", "idx")
        acked_path = os.path.join(tmp_root, f"wal-{name}", "acked.json")
        os.makedirs(os.path.dirname(workdir), exist_ok=True)
        proc = subprocess.run(
            [sys.executable, child_path, workdir, acked_path],
            env=env, capture_output=True, text=True,
            timeout=CASE_TIMEOUT_S,
        )
        rec: dict = {"exit": proc.returncode}
        try:
            with open(acked_path) as f:
                acked = json.load(f)
            mi = MutableIndex.open(workdir)
            ref = child.replay_reference(acked["ops"])
            probes = [("k003",), ("a05",), ("b02",), ("zz",)]
            mi.find_rows_many(probes)  # warm-up
            with RecompileWatch() as w:
                got = mi.find_rows_many(probes)
            rec.update(
                crashed=acked["crashed"] is not None,
                acked=len(acked["ops"]),
                recovered_records=mi.recovered_records,
                truncated_bytes=mi.recovery_info["truncated_bytes"],
                parity=index_checksums(mi.to_index())
                == index_checksums(ref.to_index()),
                answers=[[dict(r) for r in b] for b in got]
                == [[dict(r) for r in b] for b in ref.find_rows_many(probes)],
                warm_recompiles=sum(w.delta().values()),
            )
            rec["ok"] = bool(
                proc.returncode == (3 if fault is not None else 0)
                and rec["crashed"] == (fault is not None)
                and rec["acked"] == n_acked
                and rec["recovered_records"] == n_replay
                and rec["parity"]
                and rec["answers"]
                and rec["warm_recompiles"] == 0
            )
        except Exception as exc:  # a window that cannot recover at all
            rec["ok"] = False
            rec["error"] = f"{type(exc).__name__}: {exc}"
            rec["stderr_tail"] = proc.stderr[-500:]
        windows[name] = rec
    return {
        "ok": all(v["ok"] for v in windows.values()),
        "windows_total": len(windows),
        "windows_failed": sorted(
            k for k, v in windows.items() if not v["ok"]
        ),
        "windows": windows,
    }


# ---- materialized views: refresh crash window (ISSUE 12) -----------------


def case_view_refresh_crash():
    """A fatal fault at the top of the view-refresh pass inside a
    serving write cycle: the prior epoch-pinned snapshot stays live,
    the events stay queued, the dispatcher survives — and the disarmed
    retry converges back to from-scratch parity.  The crash window
    leaves a flight dump naming the views:refresh fault site."""
    from csvplus_tpu import plan as P
    from csvplus_tpu.index import create_index
    from csvplus_tpu.resilience import faults
    from csvplus_tpu.resilience.faults import FaultPlan
    from csvplus_tpu.row import Row
    from csvplus_tpu.serve import LookupServer
    from csvplus_tpu.source import take_rows
    from csvplus_tpu.storage import MutableIndex

    n_cust, n_prod = 40, 12

    def order(i):
        return Row({
            "oid": f"o{i:05d}",
            "cust_id": f"c{i % n_cust:03d}",
            "prod_id": f"p{i % n_prod:03d}",
        })

    mi = MutableIndex.create(
        take_rows([order(i) for i in range(1500)]), ["oid"],
        ingest_device="cpu",
    )
    cust = create_index(
        take_rows([Row({"cust_id": f"c{i:03d}", "name": f"n{i:03d}"})
                   for i in range(n_cust)]),
        ["cust_id"],
    )
    cust.on_device("cpu")
    prod = create_index(
        take_rows([Row({"prod_id": f"p{i:03d}", "label": f"l{i:03d}"})
                   for i in range(n_prod)]),
        ["prod_id"],
    )
    prod.on_device("cpu")
    root = P.Join(
        P.Join(P.Scan(None), cust, ("cust_id",)), prod, ("prod_id",)
    )
    with _flight_dir() as flight_dir, \
            LookupServer(indexes={"orders": mi}) as srv:
        view = srv.register_view("enriched", root, source="orders")
        base_cs = view.checksums()
        snap0, epoch0 = view.snapshot(), view.epoch
        with faults.active(
            FaultPlan(
                [{"site": "views:refresh", "at": [0], "error": "fatal"}],
                seed=17,
            )
        ) as plan:
            # the write cycle lands its tier + tombstone, then its
            # refresh pass crashes (caught by the dispatcher's sweep)
            fa = srv.submit_append([order(2000)], index="orders")
            fd = srv.submit_delete(("o00007",), index="orders")
            acked = fa.result(timeout=30.0) == 1 and fd.result(timeout=30.0) == 1
            deadline = time.perf_counter() + 30.0
            failures = 0
            while time.perf_counter() < deadline:
                cell = srv.snapshot()["by_view"].get("enriched", {})
                failures = int(cell.get("failures", 0))
                if failures:
                    break
                time.sleep(0.01)
            # the prior snapshot is still the live one: same object,
            # same epoch, same contents; the events are still queued
            intact = (
                view.snapshot() is snap0
                and view.epoch == epoch0
                and view.checksums() == base_cs
                and view.pending >= 1
            )
            injections = plan.snapshot()
        # dispatcher alive: this lookup's cycle also retries the (now
        # disarmed) refresh and drains the queue
        alive = srv.lookup("o00005", index="orders") != []
        deadline = time.perf_counter() + 30.0
        while view.pending and time.perf_counter() < deadline:
            time.sleep(0.01)
        converged = view.pending == 0
        parity = view.checksums() == view.recompute_checksums()
        resurrect_gone = view.read("o00007") == []
        cell = srv.snapshot()["by_view"]["enriched"]
        flight = _flight_evidence(flight_dir, "views:refresh")
    return {
        "ok": acked
        and failures >= 1
        and intact
        and alive
        and converged
        and parity
        and resurrect_gone
        and injections["fired"].get("views:refresh", 0) == 1
        and flight["ok"],
        "write_futures_acked": acked,
        "refresh_failures_recorded": failures,
        "prior_snapshot_intact": intact,
        "dispatcher_alive": alive,
        "retry_converged": converged,
        "from_scratch_parity": parity,
        "flight": flight,
        "injections": injections,
        "view_cell": {
            k: cell[k] for k in ("refreshes", "events", "failures", "epoch")
        },
    }


# ---- disarmed-hook overhead gate -----------------------------------------


def case_disarmed_overhead(idx, ids):
    """The disarmed inject() fast path, priced against served requests
    in BOTH regimes the sites actually run in (same discipline as
    `make trace-smoke`).  The two serve-path sites (`serve:dispatch`,
    `serve:bounds`) each fire once per dispatch CYCLE, so:

    - coalesced regime: the per-cycle site cost, amortized over the
      observed mean batch, vs the amortized per-request time;
    - isolated regime (batch of one): the full two-site cost vs one
      warm isolated submit->result round trip.

    The original formulation charged the per-cycle sites per REQUEST
    against the amortized per-request time — a worst-case numerator
    over a best-case denominator — and only stayed under budget while
    the measured loop was still half-cold, so the verdict flipped with
    case ordering.  The serve path is now fully warmed (one complete
    probe-set pass) before anything is timed, and each regime compares
    like with like."""
    from csvplus_tpu.resilience import faults
    from csvplus_tpu.serve import LookupServer

    assert faults.current() is None
    reps = 200_000
    t0 = time.perf_counter()
    for _ in range(reps):
        faults.inject("serve:bounds")
    per_call_s = (time.perf_counter() - t0) / reps

    probes = _probes(ids, 2000, seed=3)
    sites_per_cycle = 2  # serve:dispatch + serve:bounds
    with LookupServer(idx) as srv:
        for f in [srv.submit(p) for p in probes]:  # full warm pass
            f.result(timeout=30.0)
        ticks_before = srv.snapshot()["ticks"]
        t0 = time.perf_counter()
        for f in [srv.submit(p) for p in probes]:
            f.result(timeout=30.0)
        per_request_s = (time.perf_counter() - t0) / len(probes)
        cycles = max(1, srv.snapshot()["ticks"] - ticks_before)
        mean_batch = len(probes) / cycles
        iso = probes[:64]
        t0 = time.perf_counter()
        for p in iso:
            srv.submit(p).result(timeout=30.0)
        iso_rt_s = (time.perf_counter() - t0) / len(iso)

    pct_coalesced = (
        100.0 * sites_per_cycle * per_call_s / (mean_batch * per_request_s)
    )
    pct_isolated = 100.0 * sites_per_cycle * per_call_s / iso_rt_s
    pct = max(pct_coalesced, pct_isolated)
    return {
        "ok": pct <= OVERHEAD_BUDGET_PCT,
        "per_call_ns": round(per_call_s * 1e9, 2),
        "per_request_us": round(per_request_s * 1e6, 2),
        "isolated_rt_us": round(iso_rt_s * 1e6, 2),
        "mean_batch": round(mean_batch, 1),
        "sites_per_cycle": sites_per_cycle,
        "overhead_pct_coalesced": round(pct_coalesced, 4),
        "overhead_pct_isolated": round(pct_isolated, 4),
        "overhead_pct": round(pct, 4),
        "budget_pct": OVERHEAD_BUDGET_PCT,
    }


def main() -> int:
    import tempfile

    import jax

    from csvplus_tpu.obs.memory import host_header
    from csvplus_tpu.utils.observe import telemetry

    sys.stderr.write(
        f"chaos: backend={jax.default_backend()}"
        f" devices={jax.device_count()}\n"
    )
    idx, ids = _build_index()
    cases: dict = {}
    telemetry.enabled = True
    telemetry.reset()
    try:
        with tempfile.TemporaryDirectory(prefix="csvplus-chaos-") as tmp_root:
            cases["serve_retry"] = _with_timeout(
                "serve_retry", lambda: case_serve_retry(idx, ids)
            )
            cases["serve_degrade"] = _with_timeout(
                "serve_degrade", lambda: case_serve_degrade(idx, ids)
            )
            cases["dispatcher_crash"] = _with_timeout(
                "dispatcher_crash", lambda: case_dispatcher_crash(idx, ids)
            )
            cases["ingest_crash_recovery"] = _with_timeout(
                "ingest_crash_recovery",
                lambda: case_ingest_crash_recovery(tmp_root),
            )
            cases["ingest_read_fault_typed"] = _with_timeout(
                "ingest_read_fault_typed",
                lambda: case_ingest_read_fault_typed(tmp_root),
            )
            cases["mesh_join_under_ingest_faults"] = _with_timeout(
                "mesh_join", lambda: case_mesh_join_under_ingest_faults(tmp_root)
            )
            cases["storage_compact_crash"] = _with_timeout(
                "storage_compact_crash", case_storage_compact_crash
            )
            cases["wal_crash_matrix"] = _with_timeout(
                "wal_crash_matrix",
                lambda: case_wal_crash_matrix(tmp_root),
            )
            cases["view_refresh_crash"] = _with_timeout(
                "view_refresh_crash", case_view_refresh_crash
            )
            cases["disarmed_overhead"] = _with_timeout(
                "disarmed_overhead", lambda: case_disarmed_overhead(idx, ids)
            )
    finally:
        telemetry_json = telemetry.to_json()
        telemetry.enabled = False

    failed = sorted(k for k, v in cases.items() if not v.get("ok"))
    record = {
        "metric": "chaos_cases_passed",
        "value": len(cases) - len(failed),
        "cases_total": len(cases),
        "failed": failed,
        "case_timeout_s": CASE_TIMEOUT_S,
        "backend": jax.default_backend(),
        **host_header(),
        "cases": cases,
        "telemetry": telemetry_json,
    }
    try:
        record["commit"] = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, cwd=REPO, timeout=10,
        ).stdout.strip() or None
    except Exception:
        pass

    with open(ARTIFACT, "w") as f:
        json.dump(record, f, indent=1)
        f.write("\n")
    sys.stderr.write(f"chaos: artifact written to {ARTIFACT}\n")

    compact = {
        k: record[k]
        for k in ("metric", "value", "cases_total", "failed", "backend")
    }
    compact["overhead_pct"] = cases.get("disarmed_overhead", {}).get(
        "overhead_pct"
    )
    print(json.dumps(compact), flush=True)
    if failed:
        sys.stderr.write(f"chaos FAIL: {', '.join(failed)}\n")
        return 1
    sys.stderr.write(f"chaos ok: {len(cases)}/{len(cases)} cases\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
