#!/usr/bin/env python
"""`make bench-delta`: mutable-index (LSM delta-tier) bench + gate.

Drives :class:`csvplus_tpu.storage.MutableIndex` over the big-index
micro shape (same key distribution as `make bench-serve`), measuring the
three numbers the storage tier's docs promise (docs/STORAGE.md):

- append-rows/s        rows/s through ``append_rows`` — each batch rides
                       the staged streamed-ingest encode path and lands
                       as one sorted delta tier
- lookup p50/p99       per-probe ``find_rows`` latency at 0, 4 and 16
                       live delta tiers (the read amplification curve a
                       serving deployment actually sits on)
- compaction pause     reader-observed lookup latency while a full
                       compaction merges and swaps concurrently, plus
                       the compaction's own wall time — the "no lock on
                       the probe hot path" claim, measured

The ISSUE 9 hard contract is enforced INSIDE the bench, not just in the
unit suite: after EVERY compaction step the live tier set must
checksum-match a from-scratch host rebuild of the same logical rows
(bitwise), and warm lookups against the compacted index must record
zero recompiles (``RecompileWatch.assert_zero``).  ISSUE 10 extends the
stream with interleaved deletes: a tombstone cycle (deletes + appends,
one leveled fold, one full merge) must hold the same parity at every
step.  A contract breach raises — it is never a postmortem.

Contract (matches the other benches): diagnostics go to stderr, stdout
carries ONE compact JSON record line re-printed last; the run exits
nonzero only when a gated rate falls under HALF the checked-in floor
(bench_delta_floor.json) — record-or-postmortem, so a miss of the
aspirational targets embeds evidence instead of failing the gate.

Env knobs: CSVPLUS_BENCH_DELTA_ROWS (base rows, default 200K),
_APPEND_ROWS (rows per delta batch, default 2000), _LOOKUPS (probes per
latency scenario, default 1500), _OUT (artifact path; no file by
default so a gate run cannot overwrite the checked-in record).  Seeds
are fixed: same shape -> same probe sequence.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def _build_mutable(n: int):
    """A device-backed base tier on the bench-serve key shape, wrapped
    as an append-mode MutableIndex."""
    import numpy as np

    import csvplus_tpu as cp
    from csvplus_tpu.columnar.table import DeviceTable
    from csvplus_tpu.storage import MutableIndex

    ids = np.arange(n, dtype=np.int64) * 7 % (n * 3)
    keys = np.char.add("c", ids.astype(np.str_))
    t = DeviceTable.from_pylists(
        {"cust_id": keys.tolist(), "v": np.arange(n).astype(np.str_).tolist()},
        device="cpu",
    )
    idx = cp.take(t).index_on("cust_id").sync()
    return MutableIndex(idx, mode="append", ingest_device="cpu"), ids


def _delta_rows(n_rows: int, start: int):
    """Fresh-key rows for one delta tier (keys beyond the base range,
    so append batches grow the keyspace the way live writes would)."""
    from csvplus_tpu.row import Row

    return [
        Row({"cust_id": f"d{start + i}", "v": f"dv{start + i}"})
        for i in range(n_rows)
    ]


def _uniform_probes(ids, n_probes: int, seed: int = 0):
    import numpy as np

    rng = np.random.default_rng(seed)
    return [f"c{int(v)}" for v in rng.choice(ids, n_probes)]


def _assert_parity(mi, label: str) -> None:
    """The hard contract, enforced in-bench: live tier set bitwise ==
    from-scratch rebuild, after every compaction step."""
    from csvplus_tpu.storage import index_checksums, rebuild_reference

    t0 = time.perf_counter()
    got = index_checksums(mi.to_index())
    ref = index_checksums(rebuild_reference(mi))
    if got != ref:
        raise AssertionError(
            f"bench[delta] PARITY BREACH at {label}: live tier set does"
            f" not checksum-match the from-scratch rebuild"
        )
    sys.stderr.write(
        f"bench[delta]: parity ok at {label}"
        f" ({time.perf_counter() - t0:.1f}s to verify)\n"
    )


def _append_scenario(mi, n_batches: int, batch_rows: int, start: int) -> dict:
    """Append *n_batches* delta batches, timing only the append calls
    (row construction is off the clock, like probe prep in the lookup
    benches)."""
    batches = [
        _delta_rows(batch_rows, start + b * batch_rows) for b in range(n_batches)
    ]
    dt = 0.0
    for rows in batches:
        t0 = time.perf_counter()
        mi.append_rows(rows)
        dt += time.perf_counter() - t0
    total = n_batches * batch_rows
    return {
        "batches": n_batches,
        "rows_per_batch": batch_rows,
        "rows": total,
        "seconds": round(dt, 4),
        "rows_per_sec": round(total / dt, 1),
        "deltas_live_after": mi.delta_count,
    }


def _lookup_scenario(mi, probes) -> dict:
    """Per-probe find_rows latency (p50/p99) at the CURRENT delta
    count.  One warm find_rows_many pays any cold lowering off the
    clock; the timed loop is one probe per call, the serving tier's
    worst-case (uncoalesced) shape."""
    import numpy as np

    mi.find_rows_many([(p,) for p in probes[:64]])
    lats = []
    t_all0 = time.perf_counter()
    for p in probes:
        t0 = time.perf_counter()
        mi.find_rows(p)
        lats.append(time.perf_counter() - t0)
    dt = time.perf_counter() - t_all0
    a = np.asarray(lats, dtype=np.float64)
    return {
        "deltas_live": mi.delta_count,
        "n": len(probes),
        "seconds": round(dt, 4),
        "lookups_per_sec": round(len(probes) / dt, 1),
        "p50_ms": round(float(np.percentile(a, 50)) * 1e3, 3),
        "p99_ms": round(float(np.percentile(a, 99)) * 1e3, 3),
        "max_ms": round(float(a.max()) * 1e3, 3),
    }


def _compaction_pause_scenario(mi, probes, n_readers: int = 2) -> dict:
    """Reader threads hammer find_rows while compact_once merges and
    swaps.  Pause = the latency of reads overlapping the compaction
    window vs reads outside it — the snapshot-swap design says the
    probe hot path never blocks on the compactor's locks."""
    import numpy as np

    stop = threading.Event()
    started = threading.Barrier(n_readers + 1)
    samples = []  # (t_start, latency) appended per-thread, merged after
    per_thread = [[] for _ in range(n_readers)]
    errs = []

    def reader(slot: int):
        local = per_thread[slot]
        try:
            started.wait()
            i = slot
            while not stop.is_set():
                p = probes[i % len(probes)]
                t0 = time.perf_counter()
                mi.find_rows(p)
                local.append((t0, time.perf_counter() - t0))
                i += n_readers
        except BaseException as e:  # surfaced after join
            errs.append(e)
            stop.set()

    threads = [
        threading.Thread(target=reader, args=(i,)) for i in range(n_readers)
    ]
    for t in threads:
        t.start()
    started.wait()
    time.sleep(0.05)  # let readers reach steady state first
    t_c0 = time.perf_counter()
    stats = mi.compact_once()
    t_c1 = time.perf_counter()
    time.sleep(0.05)  # and a post-compaction tail for the baseline
    stop.set()
    for t in threads:
        t.join()
    if errs:
        raise errs[0]
    for local in per_thread:
        samples.extend(local)

    during = np.asarray(
        [lat for (ts, lat) in samples if t_c0 <= ts <= t_c1], dtype=np.float64
    )
    outside = np.asarray(
        [lat for (ts, lat) in samples if ts < t_c0 or ts > t_c1],
        dtype=np.float64,
    )
    out = {
        "readers": n_readers,
        "reads_total": len(samples),
        "reads_during_compaction": int(during.size),
        "compact_seconds": round(t_c1 - t_c0, 4),
        "compact_stats": stats,
    }
    if during.size:
        out["during_p50_ms"] = round(float(np.percentile(during, 50)) * 1e3, 3)
        out["during_p99_ms"] = round(float(np.percentile(during, 99)) * 1e3, 3)
        out["during_max_ms"] = round(float(during.max()) * 1e3, 3)
    if outside.size:
        out["outside_p50_ms"] = round(float(np.percentile(outside, 50)) * 1e3, 3)
        out["outside_p99_ms"] = round(float(np.percentile(outside, 99)) * 1e3, 3)
    return out


def _zero_recompile_gate(mi, probes) -> dict:
    """Warm lookups against the compacted index must recompile nothing
    — the merge path promises plain-numpy merges + one device_put per
    column, never a fresh jitted shape."""
    from csvplus_tpu.obs.recompile import RecompileWatch

    norm = [(p,) for p in probes]
    mi.find_rows_many(norm)  # warm-up pays any cold lowering once
    with RecompileWatch() as w:
        for _ in range(3):
            mi.find_rows_many(norm)
    w.assert_zero("bench-delta warm post-compaction lookups")
    return {"observable": bool(w.observable()), "recompiles": 0}


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    from csvplus_tpu.obs.memory import host_header

    n = _env_int("CSVPLUS_BENCH_DELTA_ROWS", 200_000)
    batch_rows = _env_int("CSVPLUS_BENCH_DELTA_APPEND_ROWS", 2_000)
    n_lookups = _env_int("CSVPLUS_BENCH_DELTA_LOOKUPS", 1_500)
    out_path = os.environ.get("CSVPLUS_BENCH_DELTA_OUT")
    host_cpus = os.cpu_count() or 1

    sys.stderr.write(
        f"bench[delta]: building {n:,}-row base tier"
        f" (backend={jax.default_backend()}, host_cpus={host_cpus})\n"
    )
    t0 = time.perf_counter()
    mi, ids = _build_mutable(n)
    sys.stderr.write(
        f"bench[delta]: base ready in {time.perf_counter() - t0:.1f}s\n"
    )
    probes = _uniform_probes(ids, n_lookups)

    scenarios: dict = {}

    # -- read amplification curve: 0 -> 4 -> 16 live deltas ---------------
    scenarios["lookup_0_deltas"] = _lookup_scenario(mi, probes)
    sys.stderr.write(
        "bench[delta]: lookups @0 deltas"
        f" p50 {scenarios['lookup_0_deltas']['p50_ms']}ms"
        f" p99 {scenarios['lookup_0_deltas']['p99_ms']}ms\n"
    )

    scenarios["append"] = _append_scenario(mi, 4, batch_rows, start=0)
    append_rate = scenarios["append"]["rows_per_sec"]
    sys.stderr.write(
        f"bench[delta]: append {append_rate:,.0f} rows/s"
        f" ({scenarios['append']['batches']} batches of"
        f" {batch_rows:,})\n"
    )

    scenarios["lookup_4_deltas"] = _lookup_scenario(mi, probes)
    sys.stderr.write(
        "bench[delta]: lookups @4 deltas"
        f" p50 {scenarios['lookup_4_deltas']['p50_ms']}ms"
        f" p99 {scenarios['lookup_4_deltas']['p99_ms']}ms\n"
    )

    scenarios["append_to_16"] = _append_scenario(
        mi, 12, batch_rows, start=4 * batch_rows
    )
    scenarios["lookup_16_deltas"] = _lookup_scenario(mi, probes)
    lookup16 = scenarios["lookup_16_deltas"]["lookups_per_sec"]
    sys.stderr.write(
        "bench[delta]: lookups @16 deltas"
        f" p50 {scenarios['lookup_16_deltas']['p50_ms']}ms"
        f" p99 {scenarios['lookup_16_deltas']['p99_ms']}ms"
        f" ({lookup16:,.0f}/s)\n"
    )

    # -- compaction: concurrent-reader pause + hard contract --------------
    scenarios["compaction_pause"] = _compaction_pause_scenario(mi, probes)
    cp_s = scenarios["compaction_pause"]
    sys.stderr.write(
        f"bench[delta]: compaction {cp_s['compact_seconds']}s with"
        f" {cp_s['reads_during_compaction']} concurrent reads"
        f" (during p99 {cp_s.get('during_p99_ms')}ms,"
        f" outside p99 {cp_s.get('outside_p99_ms')}ms)\n"
    )
    _assert_parity(mi, "compaction step 1")

    # a second append+compact cycle: parity must hold at EVERY step
    mi.append_rows(_delta_rows(batch_rows, start=16 * batch_rows))
    stats2 = mi.compact_once()
    scenarios["second_compaction"] = stats2
    _assert_parity(mi, "compaction step 2")

    # -- tombstone cycle (ISSUE 10): interleaved appends and deletes -------
    # hold the same checksum parity through a partial (leveled) fold
    # and the full merge that drops the tombstones for good
    for i in range(8):
        mi.delete((f"d{16 * batch_rows + i}",))
    mi.append_rows(_delta_rows(64, start=17 * batch_rows))
    mi.delete((probes[0],))
    mi.append_rows(_delta_rows(64, start=17 * batch_rows + 64))
    _assert_parity(mi, "live tombstone tiers")
    step_stats = mi.compact_step(ratio=2)
    _assert_parity(mi, "leveled fold with tombstones")
    stats3 = mi.compact_once()
    scenarios["tombstone_cycle"] = {
        "deletes": 10,
        "leveled_fold": step_stats,
        "full_merge": stats3,
    }
    _assert_parity(mi, "tombstones applied and dropped")

    scenarios["zero_recompile_gate"] = _zero_recompile_gate(mi, probes[:256])
    sys.stderr.write(
        "bench[delta]: warm post-compaction lookups recompiled nothing\n"
    )

    # fence+filter pruning accounting for the whole run (ISSUE 11):
    # cumulative tiers probed/pruned and the read-amp window the
    # "readamp" Compactor policy schedules from
    prune_stats = mi.snapshot()["prune"]
    sys.stderr.write(
        f"bench[delta]: prune enabled={prune_stats.get('enabled')}"
        f" tier_probes={prune_stats.get('tier_probes')}"
        f" tiers_pruned={prune_stats.get('tiers_pruned')}"
        f" mean_tiers_probed={prune_stats.get('mean_tiers_probed')}\n"
    )

    # -- record ------------------------------------------------------------
    record = {
        "metric": "delta_append_rows_per_sec",
        "value": append_rate,
        "unit": "rows/s",
        "n_rows": n,
        "rows_per_batch": batch_rows,
        "n_lookups": n_lookups,
        "backend": jax.default_backend(),
        **host_header(),
        "lookups_per_sec_16_deltas": lookup16,
        "lookup_p99_ms_0_deltas": scenarios["lookup_0_deltas"]["p99_ms"],
        "lookup_p99_ms_16_deltas": scenarios["lookup_16_deltas"]["p99_ms"],
        "compact_seconds": cp_s["compact_seconds"],
        "prune": prune_stats,
        "scenarios": scenarios,
    }
    try:
        record["commit"] = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, cwd=REPO, timeout=10,
        ).stdout.strip() or None
    except Exception:
        pass

    if out_path:
        with open(out_path, "w") as f:
            json.dump(record, f, indent=1)
            f.write("\n")
        sys.stderr.write(f"bench[delta]: artifact written to {out_path}\n")

    # -- floor gate (record-or-postmortem: fail only under HALF floor) -----
    floors = {}
    try:
        with open(os.path.join(REPO, "bench_delta_floor.json")) as f:
            floors = json.load(f)
    except (OSError, ValueError):
        pass
    status = 0
    for key, got in (
        ("delta_append_rows_per_sec", append_rate),
        ("lookups_per_sec_16_deltas", lookup16),
    ):
        floor = float(floors.get(key, 0.0) or 0.0)
        if floor and got < floor / 2:
            sys.stderr.write(
                f"bench[delta] REGRESSION: {key} {got:,.0f} is under half"
                f" the floor ({floor:,.0f})\n"
            )
            status = 1
        else:
            sys.stderr.write(
                f"bench[delta] ok: {key} {got:,.0f} (floor {floor:,.0f})\n"
            )
    # compact record re-printed LAST on stdout (the machine-readable line)
    compact = {
        k: record[k]
        for k in (
            "metric", "value", "unit", "n_rows", "rows_per_batch",
            "n_lookups", "host_cpus", "lookups_per_sec_16_deltas",
            "lookup_p99_ms_0_deltas", "lookup_p99_ms_16_deltas",
            "compact_seconds",
        )
        if k in record
    }
    print(json.dumps(compact), flush=True)
    return status


if __name__ == "__main__":
    sys.exit(main())
