"""TPC-H-flavored macro-bench: named query chains through the PlanCache,
optimizer-on vs ``CSVPLUS_FUSE=0`` in the SAME child over identical
bytes (ISSUE 19, ROADMAP item 1's open workload).

Five named queries — multi-join star shapes, filters, projection, and
a positional ``Top`` terminal (the plan vocabulary's order-sensitive
tail; there is no sort node) — over uniform AND Zipf(s=1.1) fact keys,
one of them on the hermetic 8-device mesh.  The headline queries join
a REGION-RESTRICTED customer dimension (TPC-H Q5's shape: only ~1/7 of
fact keys find a partner), because that is where fusion's economics
live: the staged leg materializes the full post-filter width before
probing, while the fused leg probes first and gathers the wide columns
only for the rows that matched.

* ``q1_priced_orders``   — Filter→Map→Join(cust∈r1)→Select→Top over
                           the uniform fact, all wide columns live.
* ``q2_priced_skew``     — the same chain over the Zipf(s=1.1) fact.
* ``q3_star``            — Filter→Join(cust∈r1)→Join(part)→Select→Top,
                           uniform: the multiway fuse AND the probe
                           fuse compose on one chain.
* ``q4_star_mesh``       — q3's shape over a Zipf fact sharded across
                           the 8-device mesh (the leg-peak RSS tier).
* ``q5_wide_scan``       — the full-coverage dimension: every selected
                           row matches, the merge is the same
                           full-width gather in both legs, so this
                           pins the fused floor near 1.0x (the pricing
                           rule's break-even shape).

Per query, gates (nonzero exit on any failure):

1. the staged leg (``CSVPLUS_FUSE=0``) runs FIRST — ``peak_rss_mb`` is
   a process-lifetime high watermark, so leg ordering makes the RSS
   comparison honest — then the fused leg over the very same tables;
2. bitwise parity: positional per-column checksums equal across legs;
3. ``RecompileWatch.assert_zero`` across the fused leg's warm reps;
4. every fusible query's fused-leg cache must record ``fused_chains
   >= 1`` (the rewriter fired; not assumed from the env flag);
5. on the mesh query, the fused leg's peak RSS must stay within 10%
   of the staged leg's (the r06 regression guard, measured not priced);
6. at least one fused query must clear the ISSUE 19 acceptance bar:
   >= 1.25x warm throughput over its staged leg;
7. the headline (q1 fused warm rows/s) must stay above HALF the
   checked-in floor (``bench_macro_floor.json``).

Output: ONE JSON line on stdout.  ``CSVPLUS_BENCH_MACRO_OUT`` names
the artifact (per-query speedup, leg-peak RSS, and the per-stage
``obs diff`` attribution tables for both legs).  CSVPLUS_BENCH_MACRO_ROWS
scales the fact tables (default 1M — small row counts are dispatch-
dominated and flatten every leg toward 1.0x).
"""

from __future__ import annotations

import json
import os
import sys
import time


def _hermetic() -> None:
    if os.environ.get("CSVPLUS_MACRO_HERMETIC") == "1":
        return
    env = dict(os.environ)
    env["CSVPLUS_MACRO_HERMETIC"] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    os.execve(sys.executable, [sys.executable] + sys.argv, env)


def main() -> int:
    _hermetic()
    import dataclasses

    import numpy as np

    import csvplus_tpu as cp
    from bench import zipf_probe_values
    from csvplus_tpu import plan as P
    from csvplus_tpu.columnar.exec import execute_plan_view
    from csvplus_tpu.columnar.table import DeviceTable
    from csvplus_tpu.exprs import SetValue
    from csvplus_tpu.obs.diff import diff_stage_tables, format_diff
    from csvplus_tpu.obs.memory import host_header, peak_rss_mb
    from csvplus_tpu.obs.recompile import RecompileWatch
    from csvplus_tpu.parallel.mesh import make_mesh
    from csvplus_tpu.predicates import Like, Not
    from csvplus_tpu.serve import PlanCache
    from csvplus_tpu.utils.checksum import checksum_device_table

    n = int(os.environ.get("CSVPLUS_BENCH_MACRO_ROWS", 1_000_000))
    n_cust, n_part, n_wide, reps = 2_000, 500, 10, 5
    t0_all = time.perf_counter()

    def cust_index(region=None):
        ids = [
            i for i in range(n_cust) if region is None or i % 7 == region
        ]
        return cp.take(DeviceTable.from_pylists(
            {
                "cust_id": [f"c{i}" for i in ids],
                "name": [f"name{i % 997}" for i in ids],
                "region": [f"r{i % 7}" for i in ids],
            },
            device="cpu",
        )).index_on("cust_id").sync()

    # the r1 restriction is TPC-H Q5's dimension shape: the index holds
    # only customers in one region, so ~6/7 of fact rows probe to a
    # miss and the fused leg never pays their wide-column gathers
    cust_r1_idx = cust_index(region=1)
    cust_all_idx = cust_index()
    part_idx = cp.take(DeviceTable.from_pylists(
        {
            "part_id": [f"p{i}" for i in range(n_part)],
            "brand": [f"b{i % 25}" for i in range(n_part)],
        },
        device="cpu",
    )).index_on("part_id").sync()

    def fact(dist):
        rng = np.random.default_rng(7)
        if dist == "zipf":
            cust = zipf_probe_values(np.arange(n_cust), n, s=1.1, seed=7)
            part = zipf_probe_values(np.arange(n_part), n, s=1.1, seed=8)
        else:
            cust = rng.integers(0, n_cust, n)
            part = rng.integers(0, n_part, n)
        arange = np.arange(n)
        cols = {
            "cust_id": np.char.add("c", cust.astype(np.str_)).tolist(),
            "part_id": np.char.add("p", part.astype(np.str_)).tolist(),
            "cat": np.char.add("k", (arange % 16).astype(np.str_)).tolist(),
            "qty": (arange % 100).astype(np.str_).tolist(),
        }
        # every wide column stays LIVE through the final select: the
        # staged leg materializes all of them for every post-filter row,
        # the fused leg only for the ~1/7 that match the r1 dimension
        for w in range(n_wide):
            cols[f"w{w}"] = (
                np.char.add(f"v{w}_", (arange % 89).astype(np.str_))
                .tolist()
            )
        return DeviceTable.from_pylists(cols, device="cpu")

    wide_cols = tuple(f"w{w}" for w in range(n_wide))
    weak_filter = Not(Like({"cat": "k1"}))  # keeps 15/16 of the fact

    def one_join_chain(t):
        return P.Top(
            P.SelectCols(
                P.Join(
                    P.MapExpr(
                        P.Filter(P.Scan(t), weak_filter),
                        SetValue("flag", "y"),
                    ),
                    cust_r1_idx,
                    ("cust_id",),
                ),
                ("cust_id", "name", "qty", "flag") + wide_cols,
            ),
            5_000,
        )

    def star_chain(t):
        return P.Top(
            P.SelectCols(
                P.Join(
                    P.Join(
                        P.Filter(P.Scan(t), weak_filter),
                        cust_r1_idx,
                        ("cust_id",),
                    ),
                    part_idx,
                    ("part_id",),
                ),
                ("cust_id", "name", "brand", "qty") + wide_cols,
            ),
            5_000,
        )

    def wide_chain(t):
        return P.SelectCols(
            P.Join(
                P.Filter(P.Scan(t), weak_filter),
                cust_all_idx,
                ("cust_id",),
            ),
            ("cust_id", "name", "qty") + wide_cols,
        )

    mesh = make_mesh(8)
    facts = {"uniform": fact("uniform"), "zipf": fact("zipf")}
    queries = [
        ("q1_priced_orders", one_join_chain, "uniform", None),
        ("q2_priced_skew", one_join_chain, "zipf", None),
        ("q3_star", star_chain, "uniform", None),
        ("q4_star_mesh", star_chain, "zipf", mesh),
        ("q5_wide_scan", wide_chain, "zipf", None),
    ]

    def timed(cache, pl):
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            out = cache.execute(pl)
            best = min(best, time.perf_counter() - t0)
        return best, out

    def stage_seconds(root):
        """Marginal per-stage seconds via prefix execution (the same
        crude-but-honest attribution ``make bench-opt`` records)."""
        nodes = list(P.linearize(root))
        rows, prev_t, prev_rows = [], 0.0, 0
        for k in range(len(nodes)):
            node = nodes[0]
            for stage in nodes[1 : k + 1]:
                node = dataclasses.replace(stage, child=node)
            best = float("inf")
            for _ in range(2):
                t0 = time.perf_counter()
                out = execute_plan_view(node).materialize()
                best = min(best, time.perf_counter() - t0)
            rows.append(
                {
                    "stage": type(nodes[k]).__name__,
                    "seconds": round(max(best - prev_t, 0.0), 6),
                    "rows_in": prev_rows if k else out.nrows,
                    "rows_out": out.nrows,
                }
            )
            prev_t, prev_rows = best, out.nrows
        return rows

    from csvplus_tpu.analysis.rewrite import apply_recipe

    record: dict = {"rows": n, "queries": {}}
    stage_diff_text: dict = {}
    best_speedup = 0.0
    for name, builder, dist, qmesh in queries:
        t = facts[dist]
        if qmesh is not None:
            t = t.with_sharding(qmesh)
        pl = builder(t)

        # staged leg FIRST: peak_rss_mb is monotonic over the process
        # lifetime, so this ordering lets the fused leg's peak be
        # compared against (not hidden under) the staged leg's
        os.environ["CSVPLUS_FUSE"] = "0"
        try:
            cache_staged = PlanCache(size=4)
            cache_staged.execute(pl)  # cold admit + lower, staged
            t_staged, out_staged = timed(cache_staged, pl)
        finally:
            os.environ.pop("CSVPLUS_FUSE", None)
        rss_staged = peak_rss_mb()

        cache_fused = PlanCache(size=4)
        cache_fused.execute(pl)  # cold admit: pass 5 prices + fuses
        exe = cache_fused.executable_for(pl)
        steps = [s[0] for s in (exe.recipe.steps if exe.recipe else ())]
        if "fuse_chain" not in steps or cache_fused.stats()["fused_chains"] < 1:
            sys.stderr.write(
                f"bench[macro] FAIL({name}): rewriter did not fuse the"
                f" probe run (recipe steps {steps}, stats"
                f" {cache_fused.stats()})\n"
            )
            return 1
        with RecompileWatch() as watch:
            t_fused, out_fused = timed(cache_fused, pl)
        rss_fused = peak_rss_mb()

        # parity AFTER the watch: checksum kernels jit on first use
        if list(out_fused.columns) != list(out_staged.columns) or (
            checksum_device_table(out_fused, positional=True)
            != checksum_device_table(out_staged, positional=True)
        ):
            sys.stderr.write(
                f"bench[macro] FAIL({name}): fused output is not"
                f" bitwise-equal to the CSVPLUS_FUSE=0 leg's\n"
            )
            return 1
        watch.assert_zero(f"warm fused serving ({name})")

        if qmesh is not None and rss_fused > rss_staged * 1.10:
            sys.stderr.write(
                f"bench[macro] FAIL({name}): fused leg peak RSS"
                f" {rss_fused:,.0f}MB exceeds the staged leg's"
                f" {rss_staged:,.0f}MB by more than 10%\n"
            )
            return 1

        speedup = t_staged / t_fused
        best_speedup = max(best_speedup, speedup)
        record["queries"][name] = {
            "fused_rows_per_sec_warm": round(n / t_fused, 1),
            "staged_rows_per_sec_warm": round(n / t_staged, 1),
            "speedup": round(speedup, 3),
            "out_rows": out_fused.nrows,
            "recipe_steps": steps,
            "staged_leg_peak_rss_mb": round(rss_staged, 1),
            "fused_leg_peak_rss_mb": round(rss_fused, 1),
        }
        diff = diff_stage_tables(
            stage_seconds(pl), stage_seconds(apply_recipe(pl, exe.recipe))
        )
        stage_diff_text[name] = format_diff(diff, "staged", "fused")
        sys.stderr.write(
            f"bench[macro] {name}: {speedup:.2f}x"
            f" ({n / t_staged:,.0f} -> {n / t_fused:,.0f} rows/s,"
            f" rss {rss_staged:,.0f} -> {rss_fused:,.0f} MB)\n"
        )

    if best_speedup < 1.25:
        sys.stderr.write(
            f"bench[macro] FAIL: no query cleared the 1.25x fused-vs-"
            f"staged bar (best {best_speedup:.2f}x)\n"
        )
        return 1

    record.update(
        {
            "metric": "macro_fused_rows_per_sec_warm",
            "value": record["queries"]["q1_priced_orders"][
                "fused_rows_per_sec_warm"
            ],
            "unit": "rows/s",
            "best_speedup": round(best_speedup, 3),
            "parity_bitwise": True,
            "warm_recompiles": 0,
            "wall_sec": round(time.perf_counter() - t0_all, 1),
            **host_header(),
        }
    )
    print(json.dumps(record), flush=True)

    out_path = os.environ.get("CSVPLUS_BENCH_MACRO_OUT")
    if out_path:
        artifact = dict(record)
        artifact["stage_diff_text"] = stage_diff_text
        tmp = out_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(artifact, f, indent=2, sort_keys=True)
            f.write("\n")
        os.replace(tmp, out_path)
        sys.stderr.write(f"bench[macro] artifact -> {out_path}\n")

    floor = 0.0
    floor_rows = None
    try:
        repo = os.path.dirname(os.path.abspath(__file__))
        with open(os.path.join(repo, "bench_macro_floor.json")) as f:
            fl = json.load(f)
            floor = float(fl.get("macro_fused_rows_per_sec_warm", 0.0))
            floor_rows = fl.get("rows")
    except (OSError, ValueError):
        pass
    if floor and record["value"] < floor / 2:
        sys.stderr.write(
            f"bench[macro] REGRESSION: q1 fused {record['value']:,.0f}"
            f" rows/s is under half the floor ({floor:,.0f} rows/s at"
            f" {floor_rows or '?'} rows)\n"
        )
        return 1
    lines = ", ".join(
        f"{q} {v['speedup']:.2f}x" for q, v in record["queries"].items()
    )
    sys.stderr.write(
        f"bench[macro] ok: {lines} | bitwise parity all queries, zero"
        f" warm recompiles, floor {floor:,.0f} (n={n},"
        f" {record['wall_sec']}s)\n"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
