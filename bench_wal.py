#!/usr/bin/env python
"""`make bench-wal`: durable mutable-index (WAL) bench + gate.

Drives the ISSUE 10 durability layer under
:class:`csvplus_tpu.storage.MutableIndex` and measures the three
numbers docs/STORAGE.md promises for it:

- append-rows/s        rows/s through ``append_rows`` on a DURABLE
                       index under ``CSVPLUS_WAL_SYNC=always`` (every
                       record fsynced before the ack) vs ``batch``
                       (fsync deferred to the serving tier's per-cycle
                       ``wal_sync``) — the price of the ack contract
- recovery             wall time for ``MutableIndex.open`` to replay a
                       ~200K-row WAL tail through the same delta-encode
                       path live appends ride
- lookup p50/p99       per-probe ``find_rows`` latency with live
                       tombstone tiers on the read path (the shadowing
                       masks are on the hot path; they must stay cheap)
- read-amp floor       per-probe ``find_rows`` throughput at >= 128
                       LIVE tiers (row deltas + tombstone tiers) with
                       host-side fence/filter pruning (ISSUE 11) vs the
                       same probes against the fully compacted base —
                       the r11 cliff was 47x; the pruned ratio must
                       stay within 3x (asserted in-bench), results
                       bitwise-equal to the compacted truth, parity
                       held at EVERY compaction step, zero warm
                       recompiles
- readamp compactor    a sustained append+lookup mix under the
                       ``policy="readamp"`` Compactor: the observed
                       mean tiers-probed must fall under the target
                       with NO manual compaction call (asserted)

The hard contract is enforced IN-BENCH: the recovered index must
checksum-match the live one (bitwise, ``index_checksums``) and the
from-scratch logical rebuild, and warm lookups against the recovered,
tombstone-bearing index must record zero recompiles
(``RecompileWatch.assert_zero``).  A breach raises — never a
postmortem.

Contract (matches the other benches): diagnostics go to stderr, stdout
carries ONE compact JSON record line re-printed last; the run exits
nonzero only when a gated rate falls under HALF the checked-in floor
(bench_wal_floor.json) — record-or-postmortem, so a miss of the
aspirational targets embeds evidence instead of failing the gate.

Env knobs: CSVPLUS_BENCH_WAL_ROWS (base rows, default 100K),
_APPEND_ROWS (rows per append batch, default 2000), _RECOVERY_ROWS
(WAL-tail rows for the recovery scenario, default 200K), _LOOKUPS
(probes for the latency scenario, default 1000), _OUT (artifact path;
no file by default so a gate run cannot overwrite the checked-in
record).  ``CSVPLUS_MICRO_DIST=zipf`` switches the read-amp tier's
probe draws to the shared Zipf hot-key distribution
(``bench.zipf_probe_values``, the same helper ``make bench-serve``
uses); the default stays uniform so the gated floor is
apples-to-apples with the checked-in record.  Seeds are fixed: same
shape -> same probe sequence.
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def _base_source(n: int):
    """The bench-serve key shape as an ingest source (host-built; the
    durable ctor persists the base tier to the directory)."""
    import numpy as np

    from csvplus_tpu.row import Row
    from csvplus_tpu.source import take_rows

    ids = np.arange(n, dtype=np.int64) * 7 % (n * 3)
    rows = [
        Row({"cust_id": f"c{int(v)}", "v": str(i)})
        for i, v in enumerate(ids)
    ]
    return take_rows(rows), ids


def _delta_rows(n_rows: int, start: int):
    return [
        {"cust_id": f"w{start + i}", "v": f"d{start + i}"}
        for i in range(n_rows)
    ]


def _uniform_probes(ids, n_probes: int, seed: int = 0):
    import numpy as np

    rng = np.random.default_rng(seed)
    return [f"c{int(v)}" for v in rng.choice(ids, n_probes)]


def _append_scenario(directory, src, sync: str, n_batches: int,
                     batch_rows: int) -> dict:
    """Append *n_batches* durable delta batches under fsync policy
    *sync*, timing only the append calls.  ``batch`` mode pays one
    explicit ``wal_sync()`` at the end (the serving tier's per-cycle
    flush), kept ON the clock — an unsynced append is not durable yet,
    so it has not finished."""
    from csvplus_tpu.storage import MutableIndex

    mi = MutableIndex.create(
        src, ["cust_id"], mode="append", ingest_device="cpu",
        directory=directory, wal_sync=sync,
    )
    batches = [
        _delta_rows(batch_rows, b * batch_rows) for b in range(n_batches)
    ]
    dt = 0.0
    for rows in batches:
        t0 = time.perf_counter()
        mi.append_rows(rows)
        dt += time.perf_counter() - t0
    t0 = time.perf_counter()
    wal_delta = mi.wal_sync()
    dt += time.perf_counter() - t0
    total = n_batches * batch_rows
    return {
        "sync": sync,
        "batches": n_batches,
        "rows_per_batch": batch_rows,
        "rows": total,
        "seconds": round(dt, 4),
        "rows_per_sec": round(total / dt, 1),
        "wal": mi.snapshot()["wal"],
        "fsyncs_in_flight": wal_delta["fsyncs"],
    }


def _recovery_scenario(directory, src, tail_rows: int,
                       batch_rows: int) -> dict:
    """Build a durable index whose WAL tail carries *tail_rows* rows
    (plus a sprinkle of tombstones), then time a cold
    ``MutableIndex.open`` — recovery replays the tail through the same
    delta-encode path appends ride.  The recovered state must be
    bitwise-equal to the live writer's."""
    from csvplus_tpu.storage import MutableIndex, index_checksums

    mi = MutableIndex.create(
        src, ["cust_id"], mode="append", ingest_device="cpu",
        directory=directory, wal_sync="batch",
    )
    n_batches = max(1, tail_rows // batch_rows)
    for b in range(n_batches):
        mi.append_rows(_delta_rows(batch_rows, b * batch_rows))
        if b % 16 == 0:  # tombstones ride the same replay path
            mi.delete((f"w{b * batch_rows}",))
    mi.wal_sync()
    live = index_checksums(mi.to_index())
    records = mi.snapshot()["wal"]["records"]

    t0 = time.perf_counter()
    re1 = MutableIndex.open(directory)
    dt = time.perf_counter() - t0
    if index_checksums(re1.to_index()) != live:
        raise AssertionError(
            "bench[wal] PARITY BREACH: recovered index does not"
            " checksum-match the live writer"
        )
    rows = n_batches * batch_rows
    return {
        "wal_records": records,
        "recovered_records": re1.recovered_records,
        "truncated_bytes": re1.recovery_info["truncated_bytes"],
        "rows": rows,
        "seconds": round(dt, 4),
        "rows_per_sec": round(rows / dt, 1),
    }, re1


def _tombstone_lookup_scenario(mi, probes, n_tombs: int) -> dict:
    """Per-probe find_rows latency with *n_tombs* live tombstone tiers
    shadowing the read path (every probe pays the tomb-mask check)."""
    import numpy as np

    deleted = []
    for i, p in enumerate(probes):
        if len(deleted) >= n_tombs:
            break
        if i % 7 == 0 and p not in deleted:
            mi.delete((p,))
            deleted.append(p)
    mi.find_rows_many([(p,) for p in probes[:64]])  # warm off the clock
    lats = []
    t_all0 = time.perf_counter()
    for p in probes:
        t0 = time.perf_counter()
        mi.find_rows(p)
        lats.append(time.perf_counter() - t0)
    dt = time.perf_counter() - t_all0
    a = np.asarray(lats, dtype=np.float64)
    return {
        "tombstone_tiers": len(deleted),
        "deltas_live": mi.delta_count,
        "n": len(probes),
        "seconds": round(dt, 4),
        "lookups_per_sec": round(len(probes) / dt, 1),
        "p50_ms": round(float(np.percentile(a, 50)) * 1e3, 3),
        "p99_ms": round(float(np.percentile(a, 99)) * 1e3, 3),
        "max_ms": round(float(a.max()) * 1e3, 3),
    }


def _zero_recompile_gate(mi, probes) -> dict:
    from csvplus_tpu.obs.recompile import RecompileWatch

    norm = [(p,) for p in probes]
    mi.find_rows_many(norm)
    with RecompileWatch() as w:
        for _ in range(3):
            mi.find_rows_many(norm)
    w.assert_zero("bench-wal warm recovered-index lookups")
    return {"observable": bool(w.observable()), "recompiles": 0}


def _readamp_probe_values(ids, n_probes: int):
    """The read-amp tier's probe draw: uniform by default, the shared
    Zipf hot-key distribution under CSVPLUS_MICRO_DIST=zipf."""
    dist = os.environ.get("CSVPLUS_MICRO_DIST", "uniform")
    if dist == "zipf":
        from bench import zipf_probe_values

        return dist, [f"c{int(v)}" for v in zipf_probe_values(ids, n_probes)]
    return dist, _uniform_probes(ids, n_probes, seed=11)


def _timed_single_probes(mi, probes) -> float:
    """Per-probe find_rows loop (the serving single-probe shape the r11
    cliff was measured on), returning lookups/s."""
    t0 = time.perf_counter()
    for p in probes:
        mi.find_rows((p,))
    return len(probes) / (time.perf_counter() - t0)


def _readamp_scenario(directory, src, ids, n_probes: int) -> dict:
    """The ISSUE 11 tentpole number: lookup throughput at >=128 live
    tiers (row deltas AND tombstone tiers) with host fence/filter
    pruning, vs the SAME probes against the fully compacted base.

    Hard contracts, asserted in-bench:

    - pruned layered results are bitwise-equal to the compacted truth
      (per-probe row compare) and checksum-parity holds vs the
      from-scratch logical rebuild;
    - the ``to_index`` checksum is invariant at EVERY leveled
      compaction step on the way down;
    - warm pruned lookups recompile nothing;
    - layered throughput stays within 3x of the compacted floor (the
      r11 cliff was 47x).
    """
    from csvplus_tpu.obs.recompile import RecompileWatch
    from csvplus_tpu.storage import (
        MutableIndex,
        index_checksums,
        rebuild_reference,
    )

    mi = MutableIndex.create(
        src, ["cust_id"], mode="append", ingest_device="cpu",
        directory=directory, wal_sync="batch",
    )
    # 120 row tiers + 20 tombstone tiers = 140 live tiers (>= 128)
    for b in range(120):
        mi.append_rows(_delta_rows(120, 500_000 + b * 120))
        if b % 6 == 0:
            mi.delete((f"c{int(ids[(b * 131) % len(ids)])}",))
    mi.wal_sync()
    tiers_live = mi.delta_count
    if tiers_live < 128:
        raise AssertionError(
            f"bench[wal] shape bug: only {tiers_live} live tiers"
        )

    dist, probes = _readamp_probe_values(ids, n_probes)
    norm = [(p,) for p in probes]
    mi.find_rows_many(norm[:64])  # warm off the clock
    mi.readamp.take_window()  # report the mean over the timed loop only
    layered_rate = _timed_single_probes(mi, probes)
    mean_tiers = mi.readamp.take_window()
    layered_rows = [[dict(r) for r in mi.find_rows((p,))] for p in probes]
    with RecompileWatch() as w:
        mi.find_rows_many(norm[:256])
    w.assert_zero("bench-wal warm pruned lookups")
    frozen = index_checksums(mi.to_index())
    prune_stats = mi.snapshot()["prune"]

    # compact to the floor, holding the checksum at every step
    steps = 0
    while True:
        if mi.compact_step() is None:
            break
        steps += 1
        if index_checksums(mi.to_index()) != frozen:
            raise AssertionError(
                f"bench[wal] PARITY BREACH at compaction step {steps}"
            )
    mi.compact_once()
    if index_checksums(mi.to_index()) != frozen:
        raise AssertionError("bench[wal] PARITY BREACH at full compaction")
    if index_checksums(mi.to_index()) != index_checksums(
        rebuild_reference(mi)
    ):
        raise AssertionError(
            "bench[wal] PARITY BREACH vs from-scratch logical rebuild"
        )
    compacted_rows = [[dict(r) for r in mi.find_rows((p,))] for p in probes]
    if layered_rows != compacted_rows:
        raise AssertionError(
            "bench[wal] PRUNE BREACH: layered pruned results differ from"
            " the compacted truth"
        )
    mi.find_rows_many(norm[:64])
    floor_rate = _timed_single_probes(mi, probes)
    ratio = floor_rate / layered_rate
    if ratio > 3.0:
        raise AssertionError(
            f"bench[wal] READ-AMP BREACH: compacted/layered throughput"
            f" ratio {ratio:.2f}x exceeds the 3x bound"
            f" ({layered_rate:,.0f}/s layered vs {floor_rate:,.0f}/s"
            f" compacted at {tiers_live} tiers)"
        )
    return {
        "dist": dist,
        "tiers_live": tiers_live,
        "tombstone_tiers": 20,
        "n": len(probes),
        "lookups_per_sec_layered": round(layered_rate, 1),
        "lookups_per_sec_compacted": round(floor_rate, 1),
        "compacted_over_layered": round(ratio, 3),
        "mean_tiers_probed": (
            round(mean_tiers, 3) if mean_tiers is not None else None
        ),
        "compaction_steps": steps,
        "prune": prune_stats,
    }


def _readamp_compactor_scenario(timeout_s: float = 60.0) -> dict:
    """Sustained append+lookup mix under the ``readamp`` Compactor
    policy, NO manual compaction: the policy must observe the window
    mean, compact, and drive it under the target.  A non-convergence is
    a raise, not a recorded miss — the scheduler IS the feature."""
    from csvplus_tpu.row import Row
    from csvplus_tpu.source import take_rows
    from csvplus_tpu.storage import Compactor, MutableIndex

    rows = [Row({"cust_id": f"h{i % 11}", "v": str(i)}) for i in range(256)]
    mi = MutableIndex.create(
        take_rows(rows), ["cust_id"], mode="append", ingest_device="cpu",
    )
    # the hot key lives in EVERY tier, so pruning cannot mask the
    # amplification — only the compactor can fix it
    for b in range(32):
        mi.append_rows([{"cust_id": "h0", "v": f"hot{b}"}])
    probes = [("h0",)] * 8
    mi.find_rows_many(probes)
    pre_mean = mi.readamp.take_window()
    target = 4.0
    c = Compactor(
        mi, min_deltas=1, interval_s=0.005, policy="readamp",
        readamp_target=target,
    )
    t0 = time.perf_counter()
    converged_s = None
    with c:
        while time.perf_counter() - t0 < timeout_s:
            mi.append_rows([{"cust_id": "h0", "v": "more"}])
            mi.find_rows_many(probes)
            snap = c.snapshot()
            if (
                snap["last_readamp"] is not None
                and snap["last_readamp"] <= target
                and snap["compactions"] >= 1
            ):
                converged_s = time.perf_counter() - t0
                break
            time.sleep(0.01)
    if converged_s is None:
        raise AssertionError(
            f"bench[wal] READ-AMP BREACH: readamp compactor never"
            f" converged under target {target} in {timeout_s}s:"
            f" {c.snapshot()}"
        )
    snap = c.snapshot()
    return {
        "policy": "readamp",
        "target": target,
        "pre_mean_tiers_probed": round(pre_mean, 2),
        "converged_seconds": round(converged_s, 3),
        "final_mean_tiers_probed": snap["last_readamp"],
        "compactions": snap["compactions"],
        "deltas_live_after": mi.delta_count,
    }


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    from csvplus_tpu.obs.memory import host_header
    from csvplus_tpu.storage import index_checksums, rebuild_reference

    n = _env_int("CSVPLUS_BENCH_WAL_ROWS", 100_000)
    batch_rows = _env_int("CSVPLUS_BENCH_WAL_APPEND_ROWS", 2_000)
    recovery_rows = _env_int("CSVPLUS_BENCH_WAL_RECOVERY_ROWS", 200_000)
    n_lookups = _env_int("CSVPLUS_BENCH_WAL_LOOKUPS", 1_000)
    out_path = os.environ.get("CSVPLUS_BENCH_WAL_OUT")
    host_cpus = os.cpu_count() or 1

    sys.stderr.write(
        f"bench[wal]: {n:,}-row base, {batch_rows:,}-row batches"
        f" (backend={jax.default_backend()}, host_cpus={host_cpus})\n"
    )
    scenarios: dict = {}
    tmp_root = tempfile.mkdtemp(prefix="csvplus-bench-wal-")
    try:
        # -- append throughput: the price of ack-after-fsync ---------------
        for sync in ("always", "batch"):
            src, ids = _base_source(n)
            d = os.path.join(tmp_root, f"append-{sync}")
            scenarios[f"append_{sync}"] = _append_scenario(
                d, src, sync, 8, batch_rows
            )
            s = scenarios[f"append_{sync}"]
            sys.stderr.write(
                f"bench[wal]: append sync={sync} {s['rows_per_sec']:,.0f}"
                f" rows/s ({s['wal']['fsyncs']} fsyncs)\n"
            )
        always_rate = scenarios["append_always"]["rows_per_sec"]
        batch_rate = scenarios["append_batch"]["rows_per_sec"]

        # -- recovery: replay a ~200K-row WAL tail -------------------------
        src, ids = _base_source(n)
        d = os.path.join(tmp_root, "recovery")
        scenarios["recovery"], recovered = _recovery_scenario(
            d, src, recovery_rows, batch_rows
        )
        rec = scenarios["recovery"]
        sys.stderr.write(
            f"bench[wal]: recovery of {rec['rows']:,} WAL-tail rows"
            f" ({rec['recovered_records']} records) in"
            f" {rec['seconds']}s ({rec['rows_per_sec']:,.0f} rows/s)\n"
        )

        # -- tombstone lookups on the recovered index ----------------------
        probes = _uniform_probes(ids, n_lookups)
        scenarios["lookup_tombstones"] = _tombstone_lookup_scenario(
            recovered, probes, n_tombs=32
        )
        lk = scenarios["lookup_tombstones"]
        sys.stderr.write(
            f"bench[wal]: lookups with {lk['tombstone_tiers']} tombstone"
            f" tiers p50 {lk['p50_ms']}ms p99 {lk['p99_ms']}ms"
            f" ({lk['lookups_per_sec']:,.0f}/s)\n"
        )

        # -- hard contract on the recovered index --------------------------
        if index_checksums(recovered.to_index()) != index_checksums(
            rebuild_reference(recovered)
        ):
            raise AssertionError(
                "bench[wal] PARITY BREACH: recovered tier set does not"
                " checksum-match the from-scratch logical rebuild"
            )
        sys.stderr.write("bench[wal]: recovered-index parity ok\n")
        scenarios["zero_recompile_gate"] = _zero_recompile_gate(
            recovered, probes[:256]
        )
        sys.stderr.write(
            "bench[wal]: warm recovered-index lookups recompiled nothing\n"
        )

        # -- read amplification at >=128 live tiers (ISSUE 11) -------------
        src, ids = _base_source(n)
        d = os.path.join(tmp_root, "readamp")
        scenarios["readamp"] = _readamp_scenario(d, src, ids, n_lookups)
        ra = scenarios["readamp"]
        sys.stderr.write(
            f"bench[wal]: read-amp dist={ra['dist']}"
            f" {ra['lookups_per_sec_layered']:,.0f}/s at"
            f" {ra['tiers_live']} live tiers vs"
            f" {ra['lookups_per_sec_compacted']:,.0f}/s compacted"
            f" ({ra['compacted_over_layered']}x, mean"
            f" {ra['mean_tiers_probed']} tiers probed)\n"
        )
        scenarios["readamp_compactor"] = _readamp_compactor_scenario()
        rc = scenarios["readamp_compactor"]
        sys.stderr.write(
            f"bench[wal]: readamp compactor converged"
            f" {rc['pre_mean_tiers_probed']} ->"
            f" {rc['final_mean_tiers_probed']} mean tiers probed in"
            f" {rc['converged_seconds']}s ({rc['compactions']}"
            f" compactions, no manual compact)\n"
        )
    finally:
        shutil.rmtree(tmp_root, ignore_errors=True)

    # -- record ------------------------------------------------------------
    record = {
        "metric": "wal_append_rows_per_sec_always",
        "value": always_rate,
        "unit": "rows/s",
        "n_rows": n,
        "rows_per_batch": batch_rows,
        "recovery_rows": recovery_rows,
        "n_lookups": n_lookups,
        "backend": jax.default_backend(),
        **host_header(),
        "wal_append_rows_per_sec_batch": batch_rate,
        "recovery_rows_per_sec": rec["rows_per_sec"],
        "recovery_seconds": rec["seconds"],
        "lookups_per_sec_tombstones": lk["lookups_per_sec"],
        "lookup_p50_ms_tombstones": lk["p50_ms"],
        "lookups_per_sec_readamp": ra["lookups_per_sec_layered"],
        "readamp_tiers_live": ra["tiers_live"],
        "readamp_compacted_over_layered": ra["compacted_over_layered"],
        "scenarios": scenarios,
    }
    try:
        record["commit"] = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, cwd=REPO, timeout=10,
        ).stdout.strip() or None
    except Exception:
        pass

    if out_path:
        with open(out_path, "w") as f:
            json.dump(record, f, indent=1)
            f.write("\n")
        sys.stderr.write(f"bench[wal]: artifact written to {out_path}\n")

    # -- floor gate (record-or-postmortem: fail only under HALF floor) -----
    floors = {}
    try:
        with open(os.path.join(REPO, "bench_wal_floor.json")) as f:
            floors = json.load(f)
    except (OSError, ValueError):
        pass
    status = 0
    for key, got in (
        ("wal_append_rows_per_sec_always", always_rate),
        ("wal_append_rows_per_sec_batch", batch_rate),
        ("recovery_rows_per_sec", rec["rows_per_sec"]),
        ("lookups_per_sec_tombstones", lk["lookups_per_sec"]),
        ("lookups_per_sec_readamp", ra["lookups_per_sec_layered"]),
    ):
        floor = float(floors.get(key, 0.0) or 0.0)
        if floor and got < floor / 2:
            sys.stderr.write(
                f"bench[wal] REGRESSION: {key} {got:,.0f} is under half"
                f" the floor ({floor:,.0f})\n"
            )
            status = 1
        else:
            sys.stderr.write(
                f"bench[wal] ok: {key} {got:,.0f} (floor {floor:,.0f})\n"
            )
    compact = {
        k: record[k]
        for k in (
            "metric", "value", "unit", "n_rows", "rows_per_batch",
            "recovery_rows", "host_cpus", "wal_append_rows_per_sec_batch",
            "recovery_rows_per_sec", "recovery_seconds",
            "lookups_per_sec_tombstones", "lookup_p50_ms_tombstones",
            "lookups_per_sec_readamp", "readamp_tiers_live",
            "readamp_compacted_over_layered",
        )
        if k in record
    }
    print(json.dumps(compact), flush=True)
    return status


if __name__ == "__main__":
    sys.exit(main())
